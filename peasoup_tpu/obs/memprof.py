"""Measured HBM footprints: memory_analysis probes + device stats.

The planner's capacity arithmetic ran on hand-measured constants
("68 MB/row", "96 B/samp", "48 B/element" — `parallel/mesh.py`,
`search/pipeline.py`) that were calibrated once against
``memory_analysis`` output on v5e and then frozen into the source.
This module makes the measurement a first-class, repeatable probe:

* :func:`device_memory_stats` / :func:`hbm_watermark` — the ONE
  ``device.memory_stats()`` call site in the tree (obs/trace.py and
  any sampler delegate here), normalizing the backend key variants
  (``bytes_in_use`` / ``peak_bytes_in_use``) and no-opping gracefully
  (None) on backends without stats (CPU).
* :func:`memory_analysis_probe` — ``jit(fn).lower().compile()
  .memory_analysis()`` distilled to plain argument/output/temp/
  generated-code byte counts (None where the backend provides no
  analysis), the memory-side twin of
  :func:`.costmodel.xla_cost_analysis`.
* :func:`program_footprints` — the probe run over all five registered
  pipeline programs (``analysis/jaxpr_check.py``) at their
  lint-checker shapes, process-cached; :func:`memory_join` joins the
  rows against the cost model's modelled bytes at the same shapes
  (agreement bounded by :data:`MEMORY_CLOSURE_FACTOR`, the memory
  twin of ``CROSSCHECK_FACTOR``).
* :func:`memory_report` — the ``run_report.json`` ``memory`` section:
  cached footprints + model join + the live device watermark.  With
  the default ``probe=False`` it never compiles anything (a per-job
  run report must stay cheap); explicit probing happens via
  ``obs memory --probe``, bench and the tests.
* :func:`probed_bytes_per` — measured replacements for the three
  hardcoded capacity coefficients, as the marginal compiled
  working-set slope between two sizes of a small representative
  program.  Off-TPU it returns None so the calibrated constants (and
  every existing CPU test plan) stay authoritative; ``force=True``
  exercises the machinery anywhere.
"""

from __future__ import annotations

import threading

from .metrics import REGISTRY

#: documented agreement factor between the cost model's modelled bytes
#: and the compiled program's memory_analysis working set: the model
#: counts algorithmic traffic (reads + writes per element) while XLA
#: reports buffer-assignment sizes after fusion/rematerialisation, so
#: exact agreement is impossible — but drift beyond this factor means
#: the model no longer describes the compiled program
MEMORY_CLOSURE_FACTOR = 32.0

#: probe kinds -> the planner constant each replaces (documentation;
#: the call sites fall back to their hand-measured value on None)
PROBE_KINDS = ("spectrum", "row", "fold_samp")


# -- device memory stats (the one memory_stats call site) --------------------

def device_memory_stats(device) -> dict | None:
    """Normalized ``device.memory_stats()`` for one device:
    ``{"bytes_in_use", "peak_bytes_in_use"}`` — or None on backends
    without memory stats (CPU), never an exception."""
    try:
        ms = device.memory_stats()
    except Exception:
        return None
    if not ms:
        return None
    in_use = int(ms.get("bytes_in_use", 0))
    return {
        "bytes_in_use": in_use,
        "peak_bytes_in_use": int(ms.get("peak_bytes_in_use", in_use)),
    }


def hbm_watermark() -> dict | None:
    """Max normalized stats over all local devices, or None when no
    device reports memory stats — the caller treats None as
    "unsupported" and stops polling (``obs/trace.py`` delegates
    here)."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return None
    out = None
    for d in devices:
        ms = device_memory_stats(d)
        if not ms:
            continue
        if out is None:
            out = {"bytes_in_use": 0, "peak_bytes_in_use": 0}
        out["bytes_in_use"] = max(
            out["bytes_in_use"], ms["bytes_in_use"])
        out["peak_bytes_in_use"] = max(
            out["peak_bytes_in_use"], ms["peak_bytes_in_use"])
    return out


# -- compiled-program memory analysis ----------------------------------------

def memory_analysis_probe(fn, args) -> dict | None:
    """``jax.jit(fn).lower(*args).compile().memory_analysis()``
    distilled to plain byte counts, or None when the backend/jax
    version provides no analysis."""
    try:
        import jax

        compiled = jax.jit(fn).lower(*args).compile()
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if isinstance(ma, (list, tuple)):
        ma = ma[0] if ma else None
    if ma is None:
        return None

    def grab(name):
        try:
            return int(getattr(ma, name))
        except Exception:
            return 0

    out = {
        "argument_bytes": grab("argument_size_in_bytes"),
        "output_bytes": grab("output_size_in_bytes"),
        "temp_bytes": grab("temp_size_in_bytes"),
        "alias_bytes": grab("alias_size_in_bytes"),
        "generated_code_bytes": grab("generated_code_size_in_bytes"),
    }
    out["total_bytes"] = (
        out["argument_bytes"] + out["output_bytes"]
        + out["temp_bytes"] + out["generated_code_bytes"]
        - out["alias_bytes"]
    )
    return out


_cache_lock = threading.Lock()
_footprints: list[dict] | None = None
_probe_cache: dict[str, float | None] = {}


def program_footprints(refresh: bool = False) -> list[dict]:
    """memory_analysis rows for the five registered pipeline programs
    at their lint-checker shapes: ``{program, measured}`` where
    ``measured`` is a :func:`memory_analysis_probe` dict or None.
    Probes compile; the result is process-cached (``refresh=True``
    re-probes)."""
    global _footprints
    with _cache_lock:
        if _footprints is not None and not refresh:
            return [dict(r) for r in _footprints]
    from ..analysis.jaxpr_check import registered_programs

    rows: list[dict] = []
    for spec in registered_programs():
        measured = None
        try:
            fn, args = spec.build()
            measured = memory_analysis_probe(fn, args)
        except Exception:
            measured = None
        rows.append({"program": spec.name, "measured": measured})
    with _cache_lock:
        _footprints = [dict(r) for r in rows]
    return rows


def cached_footprints() -> list[dict] | None:
    """The cached :func:`program_footprints` rows, or None when no
    probe has run this process (never compiles)."""
    with _cache_lock:
        if _footprints is None:
            return None
        return [dict(r) for r in _footprints]


def reset_footprints() -> None:
    """Drop the process caches (tests)."""
    global _footprints
    with _cache_lock:
        _footprints = None
        _probe_cache.clear()


def memory_join(footprints: list[dict]) -> list[dict]:
    """Join measured footprints against the cost model's modelled
    bytes at the same shapes.

    One row per program: ``{program, model_bytes, measured,
    measured_bytes, ratio, ok}``.  ``measured_bytes`` is the compiled
    working set (argument + output + temp); ``ok`` is True when the
    ratio stays within :data:`MEMORY_CLOSURE_FACTOR` — and trivially
    True where the backend measured nothing (CPU without analysis),
    mirroring ``crosscheck_registered_programs``."""
    from .costmodel import _crosscheck_shapes

    model = _crosscheck_shapes()
    rows: list[dict] = []
    for fp in footprints:
        est = model.get(fp["program"])
        measured = fp.get("measured")
        row = {
            "program": fp["program"],
            "model_bytes": (round(est.bytes_total)
                            if est is not None else None),
            "measured": measured,
            "measured_bytes": None,
            "ratio": None,
            "ok": True,
        }
        if measured and est is not None:
            working = (measured["argument_bytes"]
                       + measured["output_bytes"]
                       + measured["temp_bytes"])
            row["measured_bytes"] = working
            if working > 0 and est.bytes_total > 0:
                ratio = est.bytes_total / working
                row["ratio"] = round(ratio, 4)
                row["ok"] = (1.0 / MEMORY_CLOSURE_FACTOR <= ratio
                             <= MEMORY_CLOSURE_FACTOR)
        rows.append(row)
    return rows


def memory_report(probe: bool = False) -> dict:
    """The ``run_report.json`` ``memory`` section.

    ``probe=False`` (the per-job default) assembles only what is
    already known — cached program footprints and the live device
    watermark; ``probe=True`` compiles the five registered programs
    first (``obs memory --probe``, bench, tests)."""
    fps = program_footprints() if probe else cached_footprints()
    out: dict = {"closure_factor": MEMORY_CLOSURE_FACTOR}
    if fps is not None:
        out["programs"] = memory_join(fps)
    wm = hbm_watermark()
    if wm is not None:
        out["watermark"] = wm
    with _cache_lock:
        probes = {k: v for k, v in _probe_cache.items()
                  if v is not None}
    if probes:
        out["probed_coefficients"] = probes
    return out


# -- planner capacity probes -------------------------------------------------

def _probe_build(kind: str, size: int):
    """``(fn, args, units, include_args)`` for one capacity probe at
    ``size``: a small representative program whose working set scales
    with the planner's unit, plus the unit count it covers at this
    size.  ``include_args`` is False where the planner constant
    budgets only the produced buffers (the dedispersion input is the
    shared filterbank, already budgeted by ``_data_bytes``)."""
    from functools import partial

    import jax.numpy as jnp

    if kind == "spectrum":
        # per live accel-spectrum element (mesh._SPECTRUM_BYTES)
        from ..search import pipeline as pl

        tim = jnp.zeros((size,), jnp.float32)
        none = jnp.zeros((0,), jnp.float32)
        fn = partial(pl.whiten_core, bin_width=1.0 / size, b5=0.05,
                     b25=0.5, use_zap=False)
        return fn, (tim, none, none), size, True
    if kind == "row":
        # per output sample per DM row (mesh "68 MB/row" planner)
        import importlib

        dd = importlib.import_module("peasoup_tpu.ops.dedisperse")
        data = jnp.zeros((16, 2 * size), jnp.float32)
        delays = jnp.zeros((4, 16), jnp.int32)
        fn = partial(dd.dedisperse, out_nsamps=size)
        return fn, (data, delays), 4 * size, False
    if kind == "fold_samp":
        # per fold sample per candidate (pipeline bytes_per_samp)
        from ..ops.fold import fold_time_series_core, optimise_device

        def fold_and_optimise(tim):
            return optimise_device(
                fold_time_series_core(tim, 0.007, 6.4e-5, 64, 16))

        return fold_and_optimise, (jnp.zeros((size,), jnp.float32),), \
            size, True
    raise ValueError(f"unknown probe kind {kind!r}")


def _probe_slope(kind: str, small: int, large: int) -> float | None:
    """Marginal working-set bytes per unit between two probe sizes."""
    measured = []
    for size in (small, large):
        try:
            fn, args, units, include_args = _probe_build(kind, size)
            ma = memory_analysis_probe(fn, args)
        except Exception:
            return None
        if ma is None:
            return None
        working = ma["output_bytes"] + ma["temp_bytes"]
        if include_args:
            working += ma["argument_bytes"]
        measured.append((units, working))
    (u0, b0), (u1, b1) = measured
    if u1 <= u0:
        return None
    slope = (b1 - b0) / float(u1 - u0)
    return slope if slope > 0 else None


#: probe sizes per kind — the lint-checker shape and its double
_PROBE_SIZES = {
    "spectrum": (2048, 4096),
    "row": (1024, 2048),
    "fold_samp": (16384, 32768),
}

#: catalogued gauge carrying each successful probe
_PROBE_GAUGES = {
    "spectrum": "hbm.probed_spectrum_bytes",
    "row": "hbm.probed_row_bytes",
    "fold_samp": "hbm.probed_fold_samp_bytes",
}


def probed_bytes_per(kind: str, force: bool = False) -> float | None:
    """Measured marginal bytes-per-unit for one planner coefficient,
    or None — the caller then falls back to its hand-measured
    constant.

    Off-TPU this returns None WITHOUT probing (the frozen constants
    are TPU HBM figures; CPU plans — and every CPU test — must not
    shift under a CPU-shaped probe).  On TPU the probe compiles two
    sizes of a small representative program once per process and
    caches the slope; a successful probe also lands in the
    ``hbm.probed_*`` gauges so telemetry and the run report carry the
    measured coefficient.  ``force=True`` probes on any backend
    (tests, ``obs memory --probe``)."""
    if kind not in _PROBE_SIZES:
        raise ValueError(f"unknown probe kind {kind!r}")
    if not force:
        try:
            import jax

            if jax.devices()[0].platform != "tpu":
                return None
        except Exception:
            return None
    with _cache_lock:
        if kind in _probe_cache:
            return _probe_cache[kind]
    small, large = _PROBE_SIZES[kind]
    slope = _probe_slope(kind, small, large)
    with _cache_lock:
        _probe_cache[kind] = slope
    if slope is not None:
        REGISTRY.gauge(_PROBE_GAUGES[kind], slope)
    return slope
