"""Thread-safe process-wide metrics registry.

Three primitive kinds, chosen for what the search drivers actually
need to report (see ISSUE/README "Observability"):

* **counters** — monotonically increasing event tallies (peak-buffer
  overflows, capacity escalations, checkpoint invalidations, ...);
* **gauges** — last-written values (HBM budget/estimate figures,
  trial-grid geometry);
* **stage timers** — accumulated per-stage durations that split
  **host wall-clock** from **device time**: the timed block calls
  ``handle.block(arrays)`` wherever it would ``block_until_ready``,
  and the measured wait is attributed to the stage as device time.
  On a remote-attached TPU that wait is device execution plus link
  latency — exactly the share of wall-clock the host cannot reclaim,
  which is the attribution ``BENCH_*.json`` previously lacked.

Jit-compile tracking: :func:`install_compile_hook` registers a
``jax.monitoring`` duration listener counting XLA backend compiles
(and their total seconds) process-wide, and
:func:`jit_program_cache_sizes` reports compiled-signature counts per
named jitted program so a recompile storm is attributable.

Everything is safe to call from worker threads; the registry uses one
re-entrant lock so nested timers on one thread cannot deadlock.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager


class _TimerHandle:
    """Yielded by :meth:`MetricsRegistry.timer`; the timed block calls
    :meth:`block` wherever it would ``block_until_ready`` so the wait
    is attributed to the stage as device time."""

    __slots__ = ("device_s",)

    def __init__(self):
        self.device_s = 0.0

    def block(self, tree):
        """``jax.block_until_ready(tree)``, charging the wait to the
        stage's device time.  Returns ``tree`` for call-through use."""
        import jax

        t0 = time.perf_counter()
        jax.block_until_ready(tree)
        self.device_s += time.perf_counter() - t0
        return tree

    def add_device_time(self, seconds: float) -> None:
        """Charge externally-measured device seconds to the stage
        (for drivers that already clock their fetches)."""
        self.device_s += float(seconds)


class MetricsCursor:
    """Opaque position marker for delta snapshots.

    One cursor per consumer: passing it to
    :meth:`MetricsRegistry.snapshot` returns the counter/timer
    *increments* since this cursor's previous snapshot (and advances
    the cursor), so periodic samplers (obs/telemetry.py) report
    per-interval rates instead of process-lifetime totals.  The cursor
    is advanced under the registry lock, so concurrent increments are
    never lost or double-counted across consecutive delta snapshots —
    every increment lands in exactly one delta.  A registry
    :meth:`~MetricsRegistry.reset` rewinds totals below the cursor;
    the next delta snapshot clamps at zero and re-bases.
    """

    __slots__ = ("_counters", "_timers")

    def __init__(self):
        self._counters: dict[str, int] = {}
        self._timers: dict[str, dict] = {}


class MetricsRegistry:
    """Counters + gauges + host/device stage timers behind one lock."""

    def __init__(self):
        self._lock = threading.RLock()
        self._counters: dict[str, int] = {}
        self._gauges: dict[str, float] = {}
        self._timers: dict[str, dict] = {}

    # -- counters / gauges -------------------------------------------------

    def inc(self, name: str, n: int = 1) -> int:
        with self._lock:
            val = self._counters.get(name, 0) + int(n)
            self._counters[name] = val
            return val

    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    # -- stage timers ------------------------------------------------------

    def observe(self, name: str, host_s: float,
                device_s: float = 0.0) -> None:
        """Accumulate one observation of a stage's duration."""
        with self._lock:
            rec = self._timers.setdefault(
                name, {"count": 0, "host_s": 0.0, "device_s": 0.0})
            rec["count"] += 1
            rec["host_s"] += float(host_s)
            rec["device_s"] += float(device_s)

    @contextmanager
    def timer(self, name: str):
        """Time a stage; nesting is fine (each level records its own
        stage).  The yielded handle attributes device waits — see
        :class:`_TimerHandle`."""
        handle = _TimerHandle()
        t0 = time.perf_counter()
        try:
            yield handle
        finally:
            self.observe(name, time.perf_counter() - t0, handle.device_s)

    # -- snapshot / reset --------------------------------------------------

    def snapshot(self, cursor: MetricsCursor | None = None) -> dict:
        """Deep-copied point-in-time view: ``{"counters", "gauges",
        "timers"}``.

        With a :class:`MetricsCursor`, the snapshot additionally
        carries ``"deltas"``: counter increments and timer
        (count/host_s/device_s) increments since the cursor's previous
        snapshot.  Both the view and the cursor advance under the one
        registry lock, so the sum of a cursor's deltas always equals
        the totals — no increment is lost to or duplicated across a
        sampling boundary.  Gauges are last-value by definition and
        have no delta.
        """
        with self._lock:
            snap = {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "timers": {k: dict(v) for k, v in self._timers.items()},
            }
            if cursor is not None:
                dc = {}
                for name, val in self._counters.items():
                    inc = val - cursor._counters.get(name, 0)
                    if inc > 0:
                        dc[name] = inc
                dt = {}
                for name, rec in self._timers.items():
                    last = cursor._timers.get(name, {})
                    inc = {
                        f: rec[f] - last.get(f, 0)
                        for f in ("count", "host_s", "device_s")
                    }
                    if any(v > 0 for v in inc.values()):
                        dt[name] = inc
                cursor._counters = dict(self._counters)
                cursor._timers = {k: dict(v)
                                  for k, v in self._timers.items()}
                snap["deltas"] = {"counters": dc, "timers": dt}
            return snap

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timers.clear()


#: process-wide registry both drivers, the CLI and bench.py report from
REGISTRY = MetricsRegistry()


_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_hook_lock = threading.Lock()
_hook_installed = False


def install_compile_hook(registry: MetricsRegistry | None = None) -> bool:
    """Count XLA backend compiles into the registry (idempotent).

    Registers a ``jax.monitoring`` duration listener: every backend
    compile increments ``jit.backend_compiles`` and accumulates into
    the ``jit_compile`` stage timer, so the report can state how much
    wall-clock went to compilation and whether a "slow" run was really
    a recompile storm.  Returns True if the hook is active.
    """
    global _hook_installed
    reg = registry if registry is not None else REGISTRY
    with _hook_lock:
        if _hook_installed:
            return True
        try:
            from jax import monitoring

            def _on_duration(event, duration, **kwargs):
                if event == _BACKEND_COMPILE_EVENT:
                    reg.inc("jit.backend_compiles")
                    reg.observe("jit_compile", float(duration))

            monitoring.register_event_duration_secs_listener(_on_duration)
        except Exception:  # pragma: no cover - jax.monitoring absent
            return False
        _hook_installed = True
        return True


def jit_program_cache_sizes() -> dict[str, int]:
    """Compiled-signature count per named jitted program.

    A jit object's cache size equals the number of distinct
    (shape, static-arg) signatures compiled through it this process —
    the per-program face of the global ``jit.backend_compiles``
    counter.  Probes the pipeline's module-level programs plus the
    mesh builders' lru caches; anything unimportable (or a jax version
    without ``_cache_size``) is simply omitted.
    """
    out: dict[str, int] = {}

    def probe(name, fn):
        size = getattr(fn, "_cache_size", None)
        try:
            if callable(size):
                out[name] = int(size())
        except Exception:
            pass

    try:
        from ..search import pipeline as pl

        probe("whiten_trial", pl.whiten_trial)
        probe("search_accel_chunk", pl.search_accel_chunk)
        probe("search_accel_chunk_legacy", pl.search_accel_chunk_legacy)
        probe("rewhiten_for_fold", pl._rewhiten_for_fold)
        probe("batched_fold_program", pl._batched_fold_program)
    except Exception:
        pass
    try:
        import sys

        # only report the mesh builders when something already imported
        # them — probing must not drag the mesh stack into a CPU-only
        # single-device process
        mesh = sys.modules.get("peasoup_tpu.parallel.mesh")
        if mesh is not None:
            out["build_fused_search"] = (
                mesh.build_fused_search.cache_info().currsize)
            out["build_chunked_search"] = (
                mesh.build_chunked_search.cache_info().currsize)
    except Exception:
        pass
    return out
