"""Per-job lifecycle timelines: durable cross-process phase marks.

A job's life between ``submitted_utc`` and ``finished_utc`` used to be
a black box once it crossed a process boundary: the spool record keeps
three wall timestamps and the worker's span tree dies with the worker.
This module is the Dapper-style lifecycle record (Sigelman et al.
2010) for the serve layer — one append-only JSONL of **marks** in the
job's own work directory (``work/<id>/timeline.jsonl``), written by
every process that touches the job:

* the spool (serve/queue.py) marks every state transition — submit,
  claim, done, failed, release, requeue, reap;
* the worker (serve/worker.py) marks phase boundaries — prefetch-hit /
  stage, batch-claim, compile, read, dedisperse, dispatch, fetch,
  decode, distill, fold, store-ingest, checkpoint-resume — by hooking
  the existing span tree (:class:`TimelineRecorder` listens on
  ``obs/trace.py`` span closes; no pipeline stage is re-instrumented).

Mark schema (one JSON object per line; ``v`` = 1)::

    {"v": 1, "phase": "<name>", "t_wall": <unix s>,
     "t_mono": <perf_counter s>, "host": "<label>", "pid": <int>,
     "attempt": <int>, ...attrs (dur_s, device_s, worker, ...)}

Every mark carries BOTH clocks: ``t_wall`` (``time.time``) is
comparable across hosts but can step; ``t_mono``
(``time.perf_counter``) never steps but is only meaningful within one
process.  The merged reader (:func:`stitch`) therefore orders marks
**within** a writer by ``t_mono`` and aligns writers **against each
other** by their wall clocks, clamped so a skewed clock can never
produce a negative gap — the same reasoning that lets the spool compute
a non-negative ``queue_wait`` from the submit mark
(:func:`queue_wait_from`).

:func:`waterfall` turns stitched marks into a partition of the job's
sojourn: the segment between two consecutive marks is attributed to
the LATER mark's phase, so ``sum(phase_s) == sojourn_s`` holds by
construction (the ``timeline`` serve verb renders this as a text
waterfall; :func:`chrome_trace_events` exports it — plus the
span-derived device durations for jobs that ran locally — as a Chrome
trace).

Cost discipline: :func:`mark` is best-effort (never raises), appends
one line with one ``open``/``write``, and self-accounts into the
``timeline.marks`` counter + ``timeline_mark`` stage timer (so
telemetry shards carry the write cost) and the process-local
:func:`overhead` tally — ``make loadgen-smoke`` gates the total under
1% of drain wall-clock, the telemetry-sampler precedent.
"""

from __future__ import annotations

import json
import os
import threading
import time

from ..utils.atomicio import atomic_write_json
from .metrics import REGISTRY as METRICS

#: mark-line schema version
TIMELINE_VERSION = 1

#: timeline filename inside a job's work directory
TIMELINE_BASENAME = "timeline.jsonl"

#: hard cap on span-derived marks per job attempt — a chunked search
#: with thousands of chunk spans must degrade to dropped marks
#: (``timeline.marks_dropped``), not an unbounded per-job file
MAX_MARKS_PER_JOB = 512

#: span name -> timeline phase for the worker-side recorder; names not
#: listed (``Job-<id>`` envelopes, per-trial ``DM-Loop`` spans) emit no
#: mark.  See CONTRIBUTING.md "Adding a timeline phase".
SPAN_PHASES = {
    "Observation-Read": "read",
    "Dedisperse": "dedisperse",
    "Accel-Search": "dispatch",
    "Fused-Search": "dispatch",
    "Chunk-Fetch": "fetch",
    "Peak-Decode": "decode",
    "Distill": "distill",
    "Folding": "fold",
    "Store-Ingest": "store-ingest",
}

#: prefix-matched span names (per-chunk spans carry their index)
SPAN_PHASE_PREFIXES = (("Chunked-Search-", "dispatch"),)

_OV_LOCK = threading.Lock()
_OVERHEAD = {"marks": 0, "seconds": 0.0, "errors": 0}


def timeline_path(work_dir: str) -> str:
    """The job's timeline file under its work directory."""
    return os.path.join(work_dir, TIMELINE_BASENAME)


def overhead() -> dict:
    """Process-cumulative mark accounting: ``{marks, seconds,
    errors}``.  The loadgen smoke sums this (plus the workers'
    ``timeline_mark`` timer deltas from their telemetry shards) to
    gate the plane's cost against drain wall-clock."""
    with _OV_LOCK:
        return dict(_OVERHEAD)


def mark(work_dir: str, phase: str, *, host: str = "",
         attempt: int = 0, t_wall: float | None = None,
         t_mono: float | None = None, registry=None, **attrs
         ) -> dict | None:
    """Append one phase mark to the job's timeline; best effort.

    Never raises: a full disk or unwritable spool costs one counted
    error (``timeline.mark_errors``), never a failed transition.
    Returns the record written, or None on failure.
    """
    t0 = time.perf_counter()
    reg = registry if registry is not None else METRICS
    rec = {
        "v": TIMELINE_VERSION,
        "phase": str(phase),
        "t_wall": round(float(t_wall) if t_wall is not None
                        else time.time(), 6),
        "t_mono": round(float(t_mono) if t_mono is not None
                        else time.perf_counter(), 6),
        "host": str(host),
        "pid": os.getpid(),
        "attempt": int(attempt),
    }
    for key, val in attrs.items():
        rec.setdefault(str(key), val)
    try:
        os.makedirs(work_dir, exist_ok=True)
        with open(timeline_path(work_dir), "a") as f:
            f.write(json.dumps(rec) + "\n")
    except (OSError, TypeError, ValueError):
        reg.inc("timeline.mark_errors")
        with _OV_LOCK:
            _OVERHEAD["errors"] += 1
        return None
    dt = time.perf_counter() - t0
    reg.inc("timeline.marks")
    reg.observe("timeline_mark", dt)
    with _OV_LOCK:
        _OVERHEAD["marks"] += 1
        _OVERHEAD["seconds"] += dt
    return rec


def read_timeline(path_or_workdir: str) -> list[dict]:
    """Every parseable mark in file order; torn/corrupt lines are
    skipped (a writer killed mid-append leaves a torn tail; that must
    never poison the merge).  Accepts the timeline file or the job's
    work directory."""
    path = path_or_workdir
    if not path.endswith(".jsonl"):
        path = timeline_path(path_or_workdir)
    out: list[dict] = []
    try:
        with open(path, "r", errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if (isinstance(rec, dict) and rec.get("phase")
                        and isinstance(rec.get("t_mono"), (int, float))
                        and isinstance(rec.get("t_wall"),
                                       (int, float))):
                    out.append(rec)
    except OSError:
        pass
    return out


# --------------------------------------------------------------------------
# stitching (clock-skew-tolerant cross-process merge)
# --------------------------------------------------------------------------

def _writer_key(m: dict) -> tuple:
    return (str(m.get("host", "")), int(m.get("pid", 0)))


def stitch(marks: list[dict]) -> list[dict]:
    """Merge marks from multiple writer processes onto one offset
    axis.

    Within a writer (``(host, pid)``) marks are ordered by its
    monotonic clock — exact, immune to wall steps.  Writers are placed
    against the reference writer (the one holding the ``submit`` mark,
    else the earliest) by their wall-clock delta at their shared spool
    boundary, clamped at >= 0 so a host whose wall clock runs behind
    cannot push its marks before the submit.  Returns copies of the
    marks with an added ``"t"`` (seconds since the first mark),
    globally sorted by ``t`` with per-writer order preserved.
    """
    valid = [m for m in marks if isinstance(m, dict)]
    if not valid:
        return []
    groups: dict[tuple, list[dict]] = {}
    for m in valid:
        groups.setdefault(_writer_key(m), []).append(m)
    for g in groups.values():
        g.sort(key=lambda m: float(m["t_mono"]))
    ordered = sorted(groups.values(),
                     key=lambda g: float(g[0]["t_wall"]))
    ref = next((g for g in ordered
                if any(m.get("phase") == "submit" for m in g)),
               ordered[0])
    ref_wall0 = float(ref[0]["t_wall"])
    out: list[tuple] = []
    for gi, g in enumerate([ref] + [g for g in ordered if g is not ref]):
        base = (0.0 if gi == 0
                else max(0.0, float(g[0]["t_wall"]) - ref_wall0))
        mono0 = float(g[0]["t_mono"])
        for m in g:
            rec = dict(m)
            rec["t"] = round(base + float(m["t_mono"]) - mono0, 6)
            out.append((rec["t"], gi, rec))
    # ties (a clamped skewed writer lands exactly on a reference
    # mark) resolve reference-writer-first: submit precedes the
    # claim it enabled
    out.sort(key=lambda item: item[:2])
    out = [rec for _, _, rec in out]
    # re-zero on the earliest mark so "t" always starts at 0.0
    t0 = out[0]["t"]
    if t0:
        for m in out:
            m["t"] = round(m["t"] - t0, 6)
    return out


def waterfall(marks: list[dict], job_id: str = "") -> dict:
    """Stitched marks -> the job's phase-partitioned waterfall.

    The interval between consecutive marks is attributed to the LATER
    mark's phase, so the phase totals sum EXACTLY to the sojourn (last
    mark minus first) — the invariant ``make loadgen-smoke`` asserts.
    """
    stitched = stitch(marks)
    segments: list[dict] = []
    phase_s: dict[str, float] = {}
    for prev, cur in zip(stitched, stitched[1:]):
        dur = max(0.0, cur["t"] - prev["t"])
        seg = {
            "phase": str(cur.get("phase", "")),
            "start_s": round(prev["t"], 6),
            "dur_s": round(dur, 6),
            "host": str(cur.get("host", "")),
            "attempt": int(cur.get("attempt", 0)),
        }
        if isinstance(cur.get("device_s"), (int, float)):
            seg["device_s"] = round(float(cur["device_s"]), 6)
        segments.append(seg)
        phase_s[seg["phase"]] = phase_s.get(seg["phase"], 0.0) + dur
    sojourn = stitched[-1]["t"] - stitched[0]["t"] if stitched else 0.0
    writers = sorted({_writer_key(m) for m in stitched})
    return {
        "v": TIMELINE_VERSION,
        "job_id": job_id,
        "marks": stitched,
        "segments": segments,
        "phase_s": {k: round(v, 6) for k, v in phase_s.items()},
        "sojourn_s": round(sojourn, 6),
        "outcome": (str(stitched[-1].get("phase", ""))
                    if stitched else ""),
        "writers": [{"host": h, "pid": p} for h, p in writers],
    }


def sojourn_for(work_dir: str) -> float | None:
    """Submit->terminal sojourn in seconds from the job's timeline
    marks, or None when the timeline is absent/unusable (the caller
    falls back to wall-clock deltas)."""
    marks = read_timeline(work_dir)
    if len(marks) < 2:
        return None
    doc = waterfall(marks)
    return doc["sojourn_s"] if doc["sojourn_s"] > 0.0 else None


def queue_wait_from(work_dir: str, *, host: str = "",
                    t_mono: float | None = None,
                    t_wall: float | None = None) -> float | None:
    """Submit->claim wait from the submit mark, never negative.

    Same writer process (host+pid match): monotonic delta — exact even
    across wall-clock steps.  Cross-process: wall delta clamped at
    >= 0, so a skewed claimer clock reads as "no wait", not a negative
    wait.  None when no submit mark exists (pre-timeline records).
    """
    sub = next((m for m in read_timeline(work_dir)
                if m.get("phase") == "submit"), None)
    if sub is None:
        return None
    if (int(sub.get("pid", -1)) == os.getpid()
            and str(sub.get("host", "")) == str(host)):
        now = time.perf_counter() if t_mono is None else float(t_mono)
        return max(0.0, now - float(sub["t_mono"]))
    now = time.time() if t_wall is None else float(t_wall)
    return max(0.0, now - float(sub["t_wall"]))


# --------------------------------------------------------------------------
# rendering / export
# --------------------------------------------------------------------------

def render_waterfall(doc: dict, width: int = 40) -> str:
    """Text waterfall of a :func:`waterfall` document (the ``timeline``
    serve verb's output)."""
    sojourn = float(doc.get("sojourn_s", 0.0))
    marks = doc.get("marks", [])
    lines = [
        f"job {doc.get('job_id') or '?'}: {len(marks)} mark(s) from "
        f"{len(doc.get('writers', []))} writer(s), sojourn "
        f"{sojourn:.3f}s -> {doc.get('outcome') or '?'}"
    ]
    segs = doc.get("segments", [])
    if not segs:
        lines.append("  (need >= 2 marks for a waterfall)")
        return "\n".join(lines)
    lines.append(f"  {'offset':>9}  {'dur':>9}  {'phase':<16} "
                 f"{'host':<10} waterfall")
    for seg in segs:
        if sojourn > 0:
            lo = int(seg["start_s"] / sojourn * width)
            hi = max(lo + 1,
                     int((seg["start_s"] + seg["dur_s"])
                         / sojourn * width))
        else:
            lo, hi = 0, 1
        bar = ("·" * lo + "█" * min(hi - lo, width - lo)).ljust(width,
                                                                "·")
        lines.append(
            f"  {seg['start_s']:>8.3f}s {seg['dur_s']:>8.3f}s  "
            f"{seg['phase']:<16} {seg['host'][:10]:<10} {bar}")
    totals = sorted(doc.get("phase_s", {}).items(),
                    key=lambda kv: -kv[1])
    parts = []
    for phase, s in totals:
        pct = (100.0 * s / sojourn) if sojourn > 0 else 0.0
        parts.append(f"{phase} {s:.3f}s ({pct:.1f}%)")
    lines.append("  phase totals: " + ", ".join(parts))
    return "\n".join(lines)


def chrome_trace_events(doc: dict, process_index: int = 0
                        ) -> list[dict]:
    """The waterfall as Chrome trace events: the lifecycle partition on
    one track, plus — for marks that carry span-derived ``dur_s`` /
    ``device_s`` (jobs that ran in a local worker) — the merged device
    spans on a second track, so Perfetto shows queue wait and device
    occupancy on one absolute axis."""
    events: list[dict] = [
        {"ph": "M", "name": "process_name", "pid": process_index,
         "tid": 0, "args": {"name": f"job {doc.get('job_id') or '?'}"}},
        {"ph": "M", "name": "thread_name", "pid": process_index,
         "tid": 0, "args": {"name": "lifecycle"}},
        {"ph": "M", "name": "thread_name", "pid": process_index,
         "tid": 1, "args": {"name": "worker spans"}},
    ]
    for seg in doc.get("segments", []):
        ts = round(seg["start_s"] * 1e6, 3)
        dur = round(seg["dur_s"] * 1e6, 3)
        events.append({
            "name": seg["phase"], "cat": "timeline", "ph": "X",
            "ts": ts, "dur": dur, "pid": process_index, "tid": 0,
            "args": {"host": seg.get("host", ""),
                     "attempt": seg.get("attempt", 0)},
        })
    for m in doc.get("marks", []):
        dur_s = m.get("dur_s")
        if not isinstance(dur_s, (int, float)) or dur_s <= 0:
            continue
        t_end = float(m["t"])
        events.append({
            "name": str(m.get("phase", "")), "cat": "span", "ph": "X",
            "ts": round(max(0.0, t_end - float(dur_s)) * 1e6, 3),
            "dur": round(float(dur_s) * 1e6, 3),
            "pid": process_index, "tid": 1,
            "args": {
                "device_ms": round(
                    1e3 * float(m.get("device_s", 0.0) or 0.0), 3),
                "host": m.get("host", ""),
            },
        })
    return events


def write_trace_json(path: str, doc: dict) -> str:
    """Serialise :func:`chrome_trace_events` as a loadable Chrome
    trace (atomic)."""
    out = {
        "traceEvents": chrome_trace_events(doc),
        "displayTimeUnit": "ms",
        "metadata": {"tool": "peasoup-tpu timeline",
                     "job_id": doc.get("job_id", "")},
    }
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    atomic_write_json(path, out, trailing_newline=True)
    return path


# --------------------------------------------------------------------------
# worker-side span recorder
# --------------------------------------------------------------------------

def phase_for_span(name: str) -> str | None:
    """Timeline phase for a span name, or None (span not a job
    phase)."""
    phase = SPAN_PHASES.get(name)
    if phase is not None:
        return phase
    for prefix, ph in SPAN_PHASE_PREFIXES:
        if name.startswith(prefix):
            return ph
    return None


class TimelineRecorder:
    """Span-close listener turning a job's worker spans into timeline
    marks — the worker registers one around each job (or batch: every
    beam's work dir receives the shared dispatch marks) so the existing
    span instrumentation doubles as the cross-process lifecycle record
    with zero new pipeline call sites.

    Per closed span whose name maps through :data:`SPAN_PHASES`, one
    mark is written at the span's END (carrying ``dur_s`` and
    ``device_s``).  When the span observed jit compiles, a ``compile``
    mark is interpolated at ``t_start + compile_s`` first (compilation
    happens before execution), keeping the waterfall partition exact.
    Marks are capped at ``max_marks`` per recorder
    (``timeline.marks_dropped`` counts the rest).
    """

    def __init__(self, work_dirs, *, host: str = "", attempt: int = 0,
                 tracer=None, registry=None,
                 max_marks: int = MAX_MARKS_PER_JOB):
        from .trace import get_tracer

        self.work_dirs = ([work_dirs] if isinstance(work_dirs, str)
                          else list(work_dirs))
        self.host = str(host)
        self.attempt = int(attempt)
        self._tracer = tracer if tracer is not None else get_tracer()
        self._registry = registry if registry is not None else METRICS
        self.max_marks = int(max_marks)
        self.emitted = 0
        self.dropped = 0
        self._compile_s0 = self._compile_host_s()

    def _compile_host_s(self) -> float:
        rec = self._registry.snapshot().get("timers", {}).get(
            "jit_compile")
        return float(rec.get("host_s", 0.0)) if rec else 0.0

    def _emit(self, phase: str, t_mono: float, **attrs) -> None:
        if self.emitted >= self.max_marks:
            self.dropped += 1
            self._registry.inc("timeline.marks_dropped")
            return
        t_wall = self._tracer.epoch + t_mono
        for wd in self.work_dirs:
            mark(wd, phase, host=self.host, attempt=self.attempt,
                 t_wall=t_wall, t_mono=t_mono,
                 registry=self._registry, **attrs)
        self.emitted += 1

    def on_span(self, rec) -> None:
        """Tracer close listener (``rec`` is a SpanRecord)."""
        phase = phase_for_span(rec.name)
        if phase is None:
            return
        dur = max(0.0, rec.t_end - rec.t_start)
        compiles = rec.attrs.get("compiles")
        if compiles:
            c1 = self._compile_host_s()
            comp_s = min(max(0.0, c1 - self._compile_s0), dur)
            self._compile_s0 = c1
            if comp_s > 0.0:
                self._emit("compile", rec.t_start + comp_s,
                           dur_s=round(comp_s, 6),
                           compiles=int(compiles))
        self._emit(phase, rec.t_end, dur_s=round(dur, 6),
                   device_s=round(float(rec.device_s), 6))

    def __enter__(self) -> "TimelineRecorder":
        self._tracer.add_listener(self.on_span)
        return self

    def __exit__(self, *exc) -> bool:
        self._tracer.remove_listener(self.on_span)
        return False
