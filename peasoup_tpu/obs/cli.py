"""The ``obs`` CLI verb family (ISSUE 16).

``python -m peasoup_tpu.cli obs <verb>`` — the operator's door into
the flight recorder:

* ``obs ingest``  — flatten artifacts (run reports, the history
  ledger, telemetry shards, timelines, compile and lineage ledgers)
  into a warehouse directory;
* ``obs query``   — filtered rows (run/stage/host/metric/source);
* ``obs top``     — largest-valued rows for a metric prefix;
* ``obs tail``    — most recent rows;
* ``obs diff``    — structural diff of two run reports (or the last
  two bench rounds of a ledger), rendered as markdown;
* ``obs baseline`` — robust per-key baselines over a ledger plus any
  anomalies the newest round trips;
* ``obs compiles`` — the geometry-keyed compile ledger summarized per
  (program, geometry fingerprint, device kind), with cache
  engagements and per-key duration anomalies (ISSUE 18);
* ``obs memory``  — measured program footprints
  (``memory_analysis``) joined against the cost model's modelled
  bytes plus the live device watermark; ``--probe`` compiles the five
  registered programs now.

Exit codes: 0 ok; 1 when ``baseline``/``compiles``/``memory`` find
anomalies or an out-of-band closure (gate-shaped); 2 on unusable
inputs.
"""

from __future__ import annotations

import argparse
import json
import sys


def _row_line(row: dict) -> str:
    key = "/".join(p for p in (row.get("run"), row.get("stage"),
                               row.get("host")) if p)
    return (f"{row.get('ts', 0.0):>14.3f}  {row.get('source', ''):<9} "
            f"{row.get('metric', ''):<28} "
            f"{row.get('value', 0.0):>14.6f}  {key}")


def _print_rows(rows, as_json: bool) -> None:
    if as_json:
        json.dump({"rows": rows}, sys.stdout, indent=1,
                  sort_keys=True)
        print()
        return
    for row in rows:
        print(_row_line(row))
    print(f"({len(rows)} row(s))")


def _warehouse(args):
    from .warehouse import Warehouse

    return Warehouse(args.dir)


def _filters(args) -> dict:
    return {k: getattr(args, k) for k in
            ("run", "stage", "host", "metric", "source")
            if getattr(args, k, None)}


def cmd_ingest(args) -> int:
    from .history import load_history
    from .warehouse import Warehouse

    wh = Warehouse(args.dir)
    total = 0
    for path in args.report or []:
        from .diff import load_report

        try:
            report = load_report(path)
        except (OSError, ValueError) as exc:
            print(f"obs ingest: cannot read report {path!r}: {exc}",
                  file=sys.stderr)
            return 2
        total += wh.ingest_run_report(report, run=args.run or path)
    if args.ledger:
        total += wh.ingest_history(load_history(args.ledger))
    if args.ts_dir:
        total += wh.ingest_telemetry(args.ts_dir)
    if args.timeline:
        total += wh.ingest_timeline(args.timeline,
                                    run=args.run or "")
    if args.compiles:
        total += wh.ingest_compiles(args.compiles,
                                    run=args.run or "")
    if args.lineage:
        total += wh.ingest_lineage(args.lineage,
                                   run=args.run or None)
    print(f"ingested {total} row(s) into {args.dir}")
    return 0


def cmd_query(args) -> int:
    rows = _warehouse(args).rows(since=args.since, **_filters(args))
    _print_rows(rows[:args.limit] if args.limit else rows,
                args.json)
    return 0


def cmd_top(args) -> int:
    rows = _warehouse(args).top(args.n, **_filters(args))
    _print_rows(rows, args.json)
    return 0


def cmd_tail(args) -> int:
    rows = _warehouse(args).tail(args.n, **_filters(args))
    _print_rows(rows, args.json)
    return 0


def cmd_diff(args) -> int:
    from .diff import (
        diff_bench_records,
        diff_reports,
        load_report,
        render_markdown,
    )

    if args.ledger:
        from .history import load_history

        recs = [r for r in load_history(args.ledger, kinds=("bench",))
                if r.get("stage_device_s")]
        if len(recs) < 2:
            print("obs diff: need at least two bench records with "
                  "stage_device_s in the ledger", file=sys.stderr)
            return 2
        diff = diff_bench_records(
            recs[-2], recs[-1],
            label_a=recs[-2].get("ts", "previous"),
            label_b=recs[-1].get("ts", "latest"))
    else:
        if len(args.reports) != 2:
            print("obs diff: need exactly two run-report paths "
                  "(or --ledger)", file=sys.stderr)
            return 2
        try:
            a = load_report(args.reports[0])
            b = load_report(args.reports[1])
        except (OSError, ValueError) as exc:
            print(f"obs diff: {exc}", file=sys.stderr)
            return 2
        diff = diff_reports(a, b, label_a=args.reports[0],
                            label_b=args.reports[1])
    if args.json:
        json.dump(diff, sys.stdout, indent=1, sort_keys=True)
        print()
    else:
        text = render_markdown(diff)
        if args.out:
            from ..utils.atomicio import atomic_write_text

            atomic_write_text(args.out, text)
            print(f"wrote {args.out}")
        else:
            print(text, end="")
    return 0


def cmd_baseline(args) -> int:
    from .baseline import (
        baseline_table,
        funnel_anomalies,
        history_anomalies,
    )
    from .history import load_history

    records = load_history(args.ledger, kinds=("bench",))
    table = baseline_table(records, window=args.window)
    anomalies = history_anomalies(records, window=args.window,
                                  z=args.z,
                                  floor_frac=args.floor_frac)
    # selection-funnel rate bands over the serve drains (ISSUE 19)
    anomalies += funnel_anomalies(
        load_history(args.ledger, kinds=("serve",)),
        window=args.window, z=args.z, floor_frac=args.floor_frac)
    if args.json:
        json.dump({"baselines": table, "anomalies": anomalies},
                  sys.stdout, indent=1, sort_keys=True)
        print()
    else:
        if table:
            print(f"{'stage':<14} {'device kind':<14} {'n':>3} "
                  f"{'median_s':>10} {'band_s':>10} {'last_s':>10}")
            for row in table:
                print(f"{row['stage']:<14} "
                      f"{row['device_kind'] or '-':<14} "
                      f"{row['n']:>3} {row['median_s']:>10.4f} "
                      f"{row['band_s']:>10.4f} "
                      f"{row['last_s']:>10.4f}")
        else:
            print("no bench records with stage_device_s in "
                  f"{args.ledger!r}")
        for anom in anomalies:
            key = anom["key"]
            unit = "s" if anom["metric"] == "stage_device_s" else ""
            print(f"ANOMALY {key['stage']} "
                  f"[{key['device_kind'] or '-'}/"
                  f"{key['geometry'] or '-'}] {anom['metric']}: "
                  f"{anom['value']:.4f}{unit} vs median "
                  f"{anom['median']:.4f}{unit} +/- "
                  f"{anom['band']:.4f}{unit} ({anom['severity']})")
    if anomalies and args.write_ledger:
        from .baseline import write_anomalies

        write_anomalies(anomalies, args.ledger)
        print(f"appended {len(anomalies)} anomaly record(s) to "
              f"{args.ledger}")
    return 1 if anomalies else 0


def cmd_compiles(args) -> int:
    from .baseline import compile_anomalies
    from .compilation import read_compiles, summarize_compiles

    records = read_compiles(args.ledger)
    if not records:
        print(f"no compile-ledger records in {args.ledger!r}")
        return 0
    rows = summarize_compiles(records)
    anomalies = compile_anomalies(records, window=args.window)
    if args.json:
        json.dump({"compiles": rows, "anomalies": anomalies},
                  sys.stdout, indent=1, sort_keys=True)
        print()
    else:
        print(f"{'program':<22} {'geometry':<13} {'device':<12} "
              f"{'n':>4} {'recomp':>6} {'total_s':>9} {'max_s':>8}")
        for row in rows:
            print(f"{row['program'] or '-':<22} "
                  f"{row['geometry'] or '-':<13} "
                  f"{row['device_kind'] or '-':<12} "
                  f"{row['compiles']:>4} {row['recompiles']:>6} "
                  f"{row['total_s']:>9.3f} {row['max_s']:>8.3f}")
        for rec in records:
            if rec.get("kind") == "cache":
                state = "engaged" if rec.get("enabled") else "disabled"
                print(f"cache {state}: {rec.get('dir') or '-'}")
        for anom in anomalies:
            key = anom["key"]
            print(f"ANOMALY {key['stage']} "
                  f"[{key['device_kind'] or '-'}/"
                  f"{key['geometry'] or '-'}]: compile "
                  f"{anom['value']:.3f}s vs median "
                  f"{anom['median']:.3f}s +/- {anom['band']:.3f}s "
                  f"({anom['severity']})")
    return 1 if anomalies else 0


def cmd_memory(args) -> int:
    from .memprof import memory_report

    rep = memory_report(probe=args.probe)
    progs = rep.get("programs") or []
    if args.json:
        json.dump(rep, sys.stdout, indent=1, sort_keys=True)
        print()
    else:
        if progs:
            print(f"{'program':<12} {'model_B':>12} {'measured_B':>12} "
                  f"{'ratio':>8}  ok")
            for row in progs:
                meas = row.get("measured_bytes")
                ratio = row.get("ratio")
                print(f"{row['program']:<12} "
                      f"{row.get('model_bytes') or 0:>12} "
                      + (f"{meas:>12}" if meas is not None
                         else f"{'-':>12}")
                      + (f" {ratio:>8.3f}" if ratio is not None
                         else f" {'-':>8}")
                      + ("  ok" if row.get("ok") else "  OUT-OF-BAND"))
        else:
            print("no measured footprints this process "
                  "(re-run with --probe)")
        wm = rep.get("watermark")
        if wm:
            print(f"watermark: {wm['bytes_in_use']} bytes in use, "
                  f"{wm['peak_bytes_in_use']} peak")
        else:
            print("watermark: backend reports no memory stats")
        for kind, slope in sorted(
                (rep.get("probed_coefficients") or {}).items()):
            print(f"probed {kind}: {slope:.1f} B/unit")
    bad = [row for row in progs if not row.get("ok")]
    return 1 if bad else 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="peasoup obs",
        description="Peasoup-TPU flight recorder: query the unified "
                    "observability warehouse")
    sub = p.add_subparsers(dest="verb", required=True)

    def common(sp):
        sp.add_argument("--dir", default="warehouse",
                        help="warehouse directory")
        sp.add_argument("--run", default=None)
        sp.add_argument("--stage", default=None)
        sp.add_argument("--host", default=None)
        sp.add_argument("--metric", default=None,
                        help="metric name prefix")
        sp.add_argument("--source", default=None,
                        help="report|span|roofline|history|telemetry"
                             "|timeline")
        sp.add_argument("--json", action="store_true")

    sp = sub.add_parser("ingest", help="flatten artifacts into the "
                                       "warehouse")
    sp.add_argument("--dir", default="warehouse")
    sp.add_argument("--report", action="append",
                    help="run_report.json path (repeatable)")
    sp.add_argument("--ledger", default=None,
                    help="history.jsonl to ingest")
    sp.add_argument("--ts-dir", default=None,
                    help="fleet/ telemetry shard dir to ingest")
    sp.add_argument("--timeline", default=None,
                    help="timeline.jsonl (or its workdir) to ingest")
    sp.add_argument("--compiles", default=None,
                    help="compiles.jsonl compile ledger to ingest")
    sp.add_argument("--lineage", default=None,
                    help="lineage.jsonl candidate-provenance ledger "
                         "to ingest (per-mark counts + per-run "
                         "funnel rates)")
    sp.add_argument("--run", default=None,
                    help="run id to stamp on ingested report rows")
    sp.set_defaults(fn=cmd_ingest)

    sp = sub.add_parser("query", help="filtered warehouse rows")
    common(sp)
    sp.add_argument("--since", type=float, default=None,
                    help="epoch-seconds lower bound")
    sp.add_argument("--limit", type=int, default=0)
    sp.set_defaults(fn=cmd_query)

    sp = sub.add_parser("top", help="largest-valued rows")
    common(sp)
    sp.add_argument("-n", type=int, default=10)
    sp.set_defaults(fn=cmd_top)

    sp = sub.add_parser("tail", help="most recent rows")
    common(sp)
    sp.add_argument("-n", type=int, default=10)
    sp.set_defaults(fn=cmd_tail)

    sp = sub.add_parser("diff", help="structural diff of two runs")
    sp.add_argument("reports", nargs="*",
                    help="two run_report.json paths")
    sp.add_argument("--ledger", default=None,
                    help="diff the last two bench rounds of this "
                         "ledger instead")
    sp.add_argument("--out", default=None,
                    help="write markdown here instead of stdout")
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=cmd_diff)

    sp = sub.add_parser("baseline", help="robust per-key baselines "
                                         "+ anomalies")
    sp.add_argument("--ledger", default="benchmarks/history.jsonl")
    sp.add_argument("--window", type=int, default=8)
    sp.add_argument("--z", type=float, default=4.0)
    sp.add_argument("--floor-frac", type=float, default=0.4)
    sp.add_argument("--write-ledger", action="store_true",
                    help="append found anomalies to the ledger as "
                         "kind:\"anomaly\" records")
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=cmd_baseline)

    sp = sub.add_parser("compiles", help="geometry-keyed compile "
                                         "ledger summary")
    sp.add_argument("--ledger", default="compiles.jsonl",
                    help="compiles.jsonl path")
    sp.add_argument("--window", type=int, default=8)
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=cmd_compiles)

    sp = sub.add_parser("memory", help="measured HBM footprints vs "
                                       "the cost model")
    sp.add_argument("--probe", action="store_true",
                    help="compile the five registered programs and "
                         "probe memory_analysis now")
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=cmd_memory)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
