"""Rolling robust baselines + typed anomaly records (ISSUE 16).

The perf gate used to compare one headline metric against a fixed
ratio; a slow per-stage drift, or a regression confined to one
geometry or device kind, sailed under it.  This module keeps a
*robust* baseline — median and MAD (median absolute deviation) — per
warehouse key and flags departures as typed ``kind:"anomaly"``
records that the history ledger, ``serve/health.py``'s ``anomaly``
rule and ``tools/chaos.py`` all consume.

Statistics, not vibes:

* the center is the **median** (one historic outlier cannot poison
  the baseline — pinned by the PR-4 gate tests);
* the spread is the **MAD** scaled by 1.4826 (unbiased for a normal
  distribution), so the band is ``median ± z·1.4826·MAD``;
* a quiet history has MAD ≈ 0, which would flag noise — so every
  band has an **absolute floor** (``floor_frac·|median|`` and/or
  ``floor_abs``), giving the gate its fixed-threshold behaviour back
  exactly when the history is too clean to estimate spread;
* everything is a pure function of the record list — deterministic
  given checked-in history, no wall clock anywhere.

Anomaly record shape (version :data:`ANOMALY_VERSION`)::

    {"v": 1, "kind": "anomaly", "ts": <from the offending record>,
     "key": {"stage", "geometry", "device_kind", "host"},
     "metric": ..., "value": ..., "median": ..., "mad": ...,
     "band": ..., "z_score": ..., "severity": "warn"|"crit"}
"""

from __future__ import annotations

from .warehouse import geometry_fingerprint

#: scale factor making the MAD a consistent sigma estimator
MAD_SCALE = 1.4826

#: default z-score beyond which a point is anomalous
DEFAULT_Z = 4.0

#: default absolute floor as a fraction of |median| — the statistical
#: band never collapses below this, so a near-constant history keeps
#:  the old fixed-ratio behaviour
DEFAULT_FLOOR_FRAC = 0.4

#: z-score (in band units) past which an anomaly is "crit" not "warn"
CRIT_BAND_FACTOR = 2.0

ANOMALY_VERSION = 1
ANOMALY_KIND = "anomaly"


def median(values) -> float:
    vals = sorted(float(v) for v in values)
    if not vals:
        return 0.0
    mid = len(vals) // 2
    if len(vals) % 2:
        return vals[mid]
    return 0.5 * (vals[mid - 1] + vals[mid])


def robust_stats(values) -> tuple[float, float]:
    """(median, MAD) of ``values``."""
    med = median(values)
    return med, median(abs(float(v) - med) for v in values)


def baseline_band(values, *, z: float = DEFAULT_Z,
                  floor_frac: float = DEFAULT_FLOOR_FRAC,
                  floor_abs: float = 0.0) -> tuple[float, float]:
    """(median, half-width) of the acceptance band around the
    baseline: ``max(z·1.4826·MAD, floor_frac·|median|, floor_abs)``."""
    med, mad = robust_stats(values)
    half = max(float(z) * MAD_SCALE * mad,
               float(floor_frac) * abs(med), float(floor_abs))
    return med, half


def _severity(excess: float, half: float) -> str:
    return ("crit" if half > 0
            and excess > CRIT_BAND_FACTOR * half else "warn")


def make_anomaly(*, ts, key: dict, metric: str, value: float,
                 med: float, mad: float, half: float,
                 direction: str) -> dict:
    sigma = MAD_SCALE * mad
    excess = abs(float(value) - med)
    return {
        "v": ANOMALY_VERSION,
        "kind": ANOMALY_KIND,
        "ts": ts,
        "key": {
            "stage": str(key.get("stage", "")),
            "geometry": str(key.get("geometry", "")),
            "device_kind": str(key.get("device_kind", "")),
            "host": str(key.get("host", "")),
        },
        "metric": str(metric),
        "value": round(float(value), 6),
        "median": round(med, 6),
        "mad": round(mad, 6),
        "band": round(half, 6),
        "z_score": round(excess / sigma, 3) if sigma > 0 else None,
        "direction": direction,
        "severity": _severity(excess, half),
    }


def detect_point(value: float, window_values, *, ts, key: dict,
                 metric: str, z: float = DEFAULT_Z,
                 floor_frac: float = DEFAULT_FLOOR_FRAC,
                 floor_abs: float = 0.0,
                 higher_is_better: bool = False,
                 min_n: int = 3) -> dict | None:
    """Judge one head value against its trailing window; returns an
    anomaly record or ``None``.  Fewer than ``min_n`` window points
    means no baseline — vacuously healthy, never a guess."""
    window_values = [float(v) for v in window_values]
    if len(window_values) < int(min_n):
        return None
    med, half = baseline_band(window_values, z=z,
                              floor_frac=floor_frac,
                              floor_abs=floor_abs)
    value = float(value)
    if higher_is_better:
        bad = value < med - half
        direction = "low"
    else:
        bad = value > med + half
        direction = "high"
    if not bad:
        return None
    _, mad = robust_stats(window_values)
    return make_anomaly(ts=ts, key=key, metric=metric, value=value,
                        med=med, mad=mad, half=half,
                        direction=direction)


# --------------------------------------------------------------------------
# history ledger: per-stage baselines across bench rounds
# --------------------------------------------------------------------------

def _history_key(rec: dict) -> tuple[str, str]:
    cfg = rec.get("config", {}) or {}
    geom = geometry_fingerprint(cfg.get("geometry", cfg))
    kind = str((rec.get("device", {}) or {}).get("kind", ""))
    return geom, kind

#: per-stage absolute floor in seconds — micro-stages jitter by more
#: than their MAD on a shared CI host; below this a delta is noise
STAGE_FLOOR_S = 1e-3


def history_anomalies(records, *, window: int = 8,
                      z: float = DEFAULT_Z,
                      floor_frac: float = DEFAULT_FLOOR_FRAC,
                      floor_abs: float = STAGE_FLOOR_S,
                      min_n: int = 3) -> list[dict]:
    """Judge the NEWEST record of each (geometry, device kind) group
    against its trailing window, per stage: the head's
    ``stage_device_s[stage]`` outside the band yields exactly one
    anomaly attributed to that (stage, geometry, device kind) key.

    Pure and deterministic: same ledger in, same anomalies out."""
    groups: dict[tuple, list[dict]] = {}
    for rec in records:
        if rec.get("stage_device_s"):
            groups.setdefault(_history_key(rec), []).append(rec)
    anomalies: list[dict] = []
    for (geom, device_kind), recs in groups.items():
        if len(recs) < int(min_n) + 1:
            continue
        head, trail = recs[-1], recs[-1 - int(window):-1]
        for stage, value in sorted(head["stage_device_s"].items()):
            series = [float(r["stage_device_s"][stage]) for r in trail
                      if stage in r.get("stage_device_s", {})]
            anom = detect_point(
                value, series, ts=head.get("ts"),
                key={"stage": stage, "geometry": geom,
                     "device_kind": device_kind},
                metric="stage_device_s", z=z, floor_frac=floor_frac,
                floor_abs=floor_abs, min_n=min_n)
            if anom is not None:
                anomalies.append(anom)
    return anomalies


def baseline_table(records, *, window: int = 8,
                   min_n: int = 3) -> list[dict]:
    """Per-(stage, geometry, device kind) baseline summary rows for
    ``obs baseline``: n, median, MAD, band and the latest value."""
    groups: dict[tuple, list[dict]] = {}
    for rec in records:
        if rec.get("stage_device_s"):
            groups.setdefault(_history_key(rec), []).append(rec)
    table: list[dict] = []
    for (geom, device_kind), recs in sorted(groups.items()):
        stages = sorted({s for r in recs for s in r["stage_device_s"]})
        for stage in stages:
            series = [float(r["stage_device_s"][stage])
                      for r in recs[-int(window) - 1:]
                      if stage in r["stage_device_s"]]
            if len(series) < int(min_n):
                continue
            med, half = baseline_band(series[:-1] or series,
                                      floor_abs=STAGE_FLOOR_S)
            _, mad = robust_stats(series[:-1] or series)
            table.append({
                "stage": stage, "geometry": geom,
                "device_kind": device_kind, "n": len(series),
                "median_s": round(med, 6), "mad_s": round(mad, 6),
                "band_s": round(half, 6),
                "last_s": round(series[-1], 6),
            })
    return table


# --------------------------------------------------------------------------
# serve ledger: selection-funnel rate bands (ISSUE 19)
# --------------------------------------------------------------------------

#: absolute floor for a funnel-fraction band — rates are in [0, 1]
#: and jitter a few points drain-to-drain on small candidate counts
FUNNEL_FLOOR_FRAC_ABS = 0.05


def funnel_anomalies(records, *, window: int = 8,
                     z: float = DEFAULT_Z,
                     floor_frac: float = DEFAULT_FLOOR_FRAC,
                     floor_abs: float = FUNNEL_FLOOR_FRAC_ABS,
                     min_n: int = 3) -> list[dict]:
    """Judge the NEWEST drain's selection-funnel rates against the
    trailing drains' (ISSUE 19).  Serve ledger records carry the
    lineage ledger's exact accounting (``lineage_pass_frac`` =
    emitted/decoded, ``lineage_absorbed_frac`` = absorbed/decoded);
    a pass fraction *below* its baseline band means distillation
    suddenly eats more of the science (a mistuned tolerance), an
    absorbed fraction *above* band means the harmonic/DM absorbers
    collapsed the population.  Funnel-free records (no
    ``lineage_decoded``) are ignored, so a ``--no-lineage`` fleet
    never trips this.  Pure and deterministic like
    :func:`history_anomalies`."""
    recs = [r for r in records
            if r.get("kind") == "serve"
            and float((r.get("metrics", {}) or {})
                      .get("lineage_decoded", 0) or 0) > 0]
    if len(recs) < int(min_n) + 1:
        return []
    head = recs[-1]
    trail = recs[-1 - int(window):-1]
    host = str((head.get("config", {}) or {}).get("worker", ""))
    anomalies: list[dict] = []
    for name, higher_is_better in (("lineage_pass_frac", True),
                                   ("lineage_absorbed_frac", False)):
        series = [float(r["metrics"][name]) for r in trail
                  if name in r.get("metrics", {})]
        value = (head.get("metrics", {}) or {}).get(name)
        if value is None:
            continue
        anom = detect_point(
            float(value), series, ts=head.get("ts"),
            key={"stage": "distill", "host": host},
            metric=name, z=z, floor_frac=floor_frac,
            floor_abs=floor_abs, min_n=min_n,
            higher_is_better=higher_is_better)
        if anom is not None:
            anomalies.append(anom)
    return anomalies


# --------------------------------------------------------------------------
# compile ledger: per-(program, geometry, device kind) duration bands
# --------------------------------------------------------------------------

#: absolute floor in seconds for a compile-duration band — sub-10 ms
#: compile jitter on a shared host is noise, not a regression
COMPILE_FLOOR_S = 0.01


def compile_anomalies(records, *, window: int = 8,
                      z: float = DEFAULT_Z,
                      floor_frac: float = DEFAULT_FLOOR_FRAC,
                      floor_abs: float = COMPILE_FLOOR_S,
                      min_n: int = 3) -> list[dict]:
    """Judge the NEWEST compile of each (program, geometry, device
    kind) key against the key's trailing compile durations — a
    program whose compile suddenly takes far longer than its own
    baseline (an XLA upgrade, a shape canonicalization regression)
    yields one anomaly attributed to that key.  ``records`` are
    compile-ledger records (:func:`.compilation.read_compiles`);
    pure and deterministic like :func:`history_anomalies`."""
    groups: dict[tuple, list[dict]] = {}
    for rec in records:
        if rec.get("kind") != "compile":
            continue
        key = (str(rec.get("program") or ""),
               str(rec.get("geometry") or ""),
               str(rec.get("device_kind") or ""))
        groups.setdefault(key, []).append(rec)
    anomalies: list[dict] = []
    for (program, geom, device_kind), recs in groups.items():
        if len(recs) < int(min_n) + 1:
            continue
        head = recs[-1]
        trail = recs[-1 - int(window):-1]
        series = [float(r.get("duration_s") or 0.0) for r in trail]
        anom = detect_point(
            float(head.get("duration_s") or 0.0), series,
            ts=head.get("ts"),
            key={"stage": program, "geometry": geom,
                 "device_kind": device_kind},
            metric="compile_duration_s", z=z, floor_frac=floor_frac,
            floor_abs=floor_abs, min_n=min_n)
        if anom is not None:
            anomalies.append(anom)
    return anomalies


# --------------------------------------------------------------------------
# telemetry shards: fleet-presence anomalies (the chaos window check)
# --------------------------------------------------------------------------

def fleet_presence_anomalies(ts_dir: str, *, t_start: float,
                             t_end: float, bin_s: float = 1.0,
                             z: float = DEFAULT_Z,
                             floor_frac: float = 0.25,
                             min_bins: int = 8) -> list[dict]:
    """Anomalies in the *number of distinct hosts sampling* per time
    bin over ``[t_start, t_end]`` — a killed worker's shard goes
    silent, the fleet presence drops below its own baseline, and each
    offending bin yields one ``kind:"anomaly"`` record (host key
    ``"fleet"``).  Once the supervisor respawns capacity the presence
    recovers and later bins are clean — exactly the emitted-then-
    cleared shape ``tools/chaos.py`` asserts."""
    from .telemetry import read_samples

    t_start, t_end = float(t_start), float(t_end)
    bin_s = max(0.1, float(bin_s))
    n_bins = int((t_end - t_start) / bin_s)
    if n_bins < int(min_bins):
        return []
    hosts_per_bin: list[set] = [set() for _ in range(n_bins)]
    for sample in read_samples(ts_dir, since=t_start):
        idx = int((float(sample.get("ts", 0.0)) - t_start) / bin_s)
        if 0 <= idx < n_bins:
            hosts_per_bin[idx].add(sample.get("host", ""))
    counts = [float(len(hosts)) for hosts in hosts_per_bin]
    anomalies: list[dict] = []
    for idx, count in enumerate(counts):
        window = counts[:idx] + counts[idx + 1:]
        anom = detect_point(
            count, window,
            ts=round(t_start + (idx + 0.5) * bin_s, 3),
            key={"stage": "presence", "host": "fleet"},
            metric="fleet_hosts_sampling", z=z,
            floor_frac=floor_frac, higher_is_better=True,
            min_n=min_bins - 1)
        if anom is not None:
            anomalies.append(anom)
    return anomalies


# --------------------------------------------------------------------------
# ledger plumbing
# --------------------------------------------------------------------------

def write_anomalies(anomalies, ledger_path: str) -> int:
    """Append anomaly records to the history ledger verbatim (their
    ``ts`` is the offending record's, NOT "now" — determinism), so
    ``load_history(path, kinds=("anomaly",))`` and the health rule
    see them."""
    from .history import append_history

    for anom in anomalies:
        append_history(dict(anom), ledger_path)
    return len(anomalies)
