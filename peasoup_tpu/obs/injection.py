"""Synthetic-pulsar injection: synthesizer, manifests and recovery matching.

The sensitivity observatory's ground truth (ISSUE 14).  Every other
observability layer (spans, cost model, telemetry, health rules, load
curves) watches *performance*; this module supplies the known-answer
probes that watch whether the pipeline still **finds pulsars**:

* :func:`synthesize` writes a filterbank carrying a properly dispersed
  pulse train with a chosen period / DM / accel / jerk / duty cycle and
  target SNR, into fresh noise at any supported ``nbits``, and returns a
  serialisable **injection manifest** describing exactly what went in.
* :func:`match_candidates` decides whether a search recovered the
  injection: candidate vs manifest within frequency / DM / accel / jerk
  tolerances, harmonic-fold aware, using the same window formulas as the
  distiller (``search/distill.py``) so "recovered" means "would have
  survived distillation as the same signal".

The module is deliberately **jax-free** (numpy + ``io/sigproc.py``
only): it must be importable from the serve control plane, the load
generator and the health rules without dragging in a backend.  The
per-channel dispersion delay table is the same float32 arithmetic as
``ops/dedisperse.py:delay_table`` (asserted identical by
``tests/test_injection.py``), re-spelt here to keep the import graph
clean.

The acceleration/jerk smearing is resample2's own cubic index ramp run
backwards — observed sample ``m`` holds the rest-frame signal at
``m - shift(m)`` with ``shift(m) = m*af*(m-n) + m*jf*(m-n)*(m+n)``
(``af = accel*tsamp/(2c)``, ``jf = jerk*tsamp^2/(6c)``, ``n`` the
search's FFT size) — so the matching ``(accel, jerk)`` trial de-smears
the train exactly, and the per-stage SNR budget probe
(``search/pipeline.py``) can attribute every dB of loss to a concrete
stage instead of to synthesis/search model mismatch.
"""

from __future__ import annotations

import json
import os

import numpy as np

SPEED_OF_LIGHT = 299792458.0

#: dedisp's dispersion constant (MHz^2 pc^-1 cm^3 s) — must match
#: ops/dedisperse.py:DM_CONST_S so injected delays land on the same
#: sample lattice the search's dedispersion removes.
DM_CONST_S = 4.15e3

MANIFEST_VERSION = 1


def delay_table(nchans: int, dt: float, f0: float, df: float) -> np.ndarray:
    """Per-channel delay in samples per DM unit.

    Bit-identical to ``ops.dedisperse.delay_table`` (same float32
    arithmetic, same constant) without importing jax.
    """
    f = (np.float32(f0) + np.arange(nchans, dtype=np.float32) * np.float32(df))
    a = np.float32(1.0) / f
    b = np.float32(1.0) / np.float32(f0)
    return (np.float32(DM_CONST_S / dt) * (a * a - b * b)).astype(np.float32)


def _delays_in_samples(dm: float, table: np.ndarray) -> np.ndarray:
    """Integer per-channel delays, round-half-up like dedisp's kernel."""
    return np.floor(np.float32(dm) * np.float32(table) + 0.5).astype(np.int64)


def _pack_payload(data: np.ndarray, nbits: int) -> bytes:
    if nbits == 32:
        return np.ascontiguousarray(data, dtype=np.float32).tobytes()
    from peasoup_tpu.io.unpack import pack_bits

    flat = np.ascontiguousarray(data, dtype=np.uint8).ravel()
    return pack_bits(flat, nbits).tobytes()


def noise_sigma(noise_max: int) -> float:
    """Std of the uniform integer noise floor ``rng.integers(0, noise_max)``."""
    return float(np.sqrt((noise_max * noise_max - 1.0) / 12.0))


def amp_for_snr(snr: float, *, duty: float, nsamps: int, nchans: int,
                noise_max: int) -> float:
    """On-pulse amplitude that targets a spectral SNR of ``snr``.

    Radiometer-style calibration: a duty-``delta`` boxcar train of
    amplitude A over N samples x C summed channels carries matched
    amplitude ``A*sqrt(delta*N*C)`` against a noise floor of std
    ``sigma`` per sample, so ``A = snr*sigma/sqrt(delta*N*C)``.  This is
    the *injected* SNR the sensitivity sweep's transfer curves measure
    against; the recovered SNR is lower by exactly the per-stage losses
    the budget probe attributes (scalloping, harmonic mismatch,
    quantisation).
    """
    return float(snr) * noise_sigma(noise_max) / float(
        np.sqrt(duty * nsamps * nchans))


def synthesize(path: str, *, period: float | None = None,
               freq: float | None = None, dm: float = 0.0,
               accel: float = 0.0, jerk: float = 0.0, duty: float = 0.05,
               snr: float | None = None, amp: float | None = None,
               noise_max: int = 32, nsamps: int = 4096, nchans: int = 16,
               tsamp: float = 0.000256, fch1: float = 1510.0,
               foff: float = -10.0, nbits: int = 8, seed: int = 0,
               size: int | None = None, truncate_bytes: int = 0,
               data: np.ndarray | None = None) -> dict:
    """Write a filterbank carrying a known synthetic pulsar; return its
    injection manifest.

    Exactly one of ``period`` (seconds) / ``freq`` (Hz) selects the spin;
    exactly one of ``snr`` (target spectral SNR, converted through
    :func:`amp_for_snr`) / ``amp`` (raw on-pulse amplitude) selects the
    brightness.  ``size`` pins the cubic accel/jerk ramp to the search's
    FFT length (defaults to ``nsamps``) so the matched trial de-smears
    exactly.  ``data`` injects into an existing (nsamps, nchans) block
    instead of fresh uniform noise; ``truncate_bytes`` drops trailing
    payload bytes (the load generator's poison-input family).

    The noise draw is always the generator's FIRST call, so two
    manifests with the same seed and geometry share a noise floor
    regardless of what is injected into it.
    """
    from peasoup_tpu.io.sigproc import SigprocHeader, write_sigproc_header

    if (period is None) == (freq is None):
        raise ValueError("pass exactly one of period= / freq=")
    # the phase arithmetic below uses whichever spin quantity the
    # caller supplied EXACTLY: on-grid periods (an integer number of
    # samples) and literal frequencies must not pick up a reciprocal
    # round trip's ulp, or boundary pulses drift off the train
    by_period = freq is None
    if by_period:
        freq = 1.0 / period
    else:
        period = 1.0 / freq
    if amp is None and snr is None:
        raise ValueError("pass one of snr= / amp=")
    if amp is None:
        amp = amp_for_snr(snr, duty=duty, nsamps=nsamps, nchans=nchans,
                          noise_max=noise_max)
    n = int(size if size is not None else nsamps)

    if data is None:
        rng = np.random.default_rng(seed)
        data = rng.integers(0, noise_max, size=(nsamps, nchans),
                            dtype=np.uint8).astype(np.float64)
    else:
        data = np.asarray(data, dtype=np.float64).copy()
        if data.shape != (nsamps, nchans):
            raise ValueError(f"data shape {data.shape} != "
                             f"({nsamps}, {nchans})")

    # rest-frame pulse train evaluated at a fractional sample index;
    # period expressed in samples so on-grid periods (e.g. the smoke
    # recipes' 16*tsamp) place pulses exactly, while a caller-supplied
    # frequency multiplies through directly
    period_samples = period / tsamp

    def pulse(phase_idx: np.ndarray) -> np.ndarray:
        if by_period:
            phase = np.mod(phase_idx / period_samples, 1.0)
        else:
            phase = np.mod(phase_idx * tsamp * freq, 1.0)
        return (phase < duty).astype(np.float64)

    af = accel * tsamp / (2.0 * SPEED_OF_LIGHT)
    jf = jerk * tsamp * tsamp / (6.0 * SPEED_OF_LIGHT)
    m = np.arange(nsamps, dtype=np.float64)
    delays = _delays_in_samples(dm, delay_table(nchans, tsamp, fch1, foff))
    for j in range(nchans):
        # channel j sees the signal ``delays[j]`` samples late; the
        # smear ramp applies in the dedispersed frame
        md = m - delays[j]
        shift = md * af * (md - n) + md * jf * (md - n) * (md + n)
        data[:, j] += pulse(md - shift) * amp

    top = 2.0 ** nbits - 1.0 if nbits != 32 else np.inf
    if nbits == 32:
        out = data.astype(np.float32)
    else:
        out = np.minimum(np.maximum(np.round(data), 0.0), top).astype(
            np.uint8)

    hdr = SigprocHeader(nbits=nbits, nchans=nchans, tsamp=tsamp, fch1=fch1,
                        foff=foff, nsamples=nsamps)
    payload = _pack_payload(out, nbits)
    if truncate_bytes:
        payload = payload[:-truncate_bytes]
    with open(path, "wb") as f:
        write_sigproc_header(f, hdr, include_nsamples=True)
        f.write(payload)

    return {
        "v": MANIFEST_VERSION,
        "kind": "injection",
        "path": os.path.abspath(path),
        "freq": float(freq),
        "period": float(period),
        "dm": float(dm),
        "accel": float(accel),
        "jerk": float(jerk),
        "duty": float(duty),
        "target_snr": float(snr) if snr is not None else None,
        "amp": float(amp),
        "noise_max": int(noise_max),
        "nsamps": int(nsamps),
        "nchans": int(nchans),
        "tsamp": float(tsamp),
        "fch1": float(fch1),
        "foff": float(foff),
        "nbits": int(nbits),
        "seed": int(seed),
        "size": n,
    }


def save_manifest(manifest: dict, path: str) -> str:
    from ..utils.atomicio import atomic_write_json

    atomic_write_json(path, manifest, indent=1, sort_keys=True,
                      trailing_newline=True)
    return path


def load_manifest(path_or_manifest) -> dict:
    """Accept a manifest dict, or a path to a saved one."""
    if isinstance(path_or_manifest, dict):
        return path_or_manifest
    with open(path_or_manifest) as f:
        return json.load(f)


# --------------------------------------------------------------------------
# recovery matching


def _cand_field(cand, name: str, default: float = 0.0) -> float:
    if isinstance(cand, dict):
        return float(cand.get(name, default))
    return float(getattr(cand, name, default))


def _harmonically_related(f: float, f0: float, tol: float,
                          max_harm: int) -> bool:
    """Same predicate family as ``JerkDistiller.is_related``: some
    integer fold ``kk*f`` lands within ``tol`` (fractional) of some
    integer fold ``jj*f0``."""
    if f <= 0.0 or f0 <= 0.0:
        return False
    for kk in range(1, max_harm + 1):
        for jj in range(1, max_harm + 1):
            ratio = kk * f / (jj * f0)
            if 1.0 - tol < ratio < 1.0 + tol:
                return True
    return False


def match_candidates(manifest, candidates, *, tobs: float | None = None,
                     freq_tol: float = 2e-3, dm_tol: float | None = None,
                     max_harm: int = 16) -> dict:
    """Did a candidate list recover the injected pulsar?

    Frequency matching is harmonic-fold aware (a candidate at half or
    twice the injected spin counts, like the distiller's related-set
    construction).  Accel and jerk windows translate the trial mismatch
    into the fractional frequency drift it causes over ``tobs``
    (``distill.py``'s ``acc_freq`` / jerk windows): a candidate matches
    when ``|acc - accel| * tobs / c <= freq_tol`` and
    ``|jerk - jerk0| * tobs^2 / (6c) <= freq_tol`` — compared on
    magnitudes, since the recovered trial's sign convention is
    resampler-relative.  ``dm_tol`` (pc cm^-3) is enforced only when
    given: DM grids are tolerance-stepped, so the caller knows the
    meaningful window.  Returns ``{"recovered", "best", "best_snr",
    "n_matches"}`` with ``best`` the strongest matching candidate.
    """
    man = load_manifest(manifest)
    f0 = float(man["freq"])
    if tobs is None:
        tobs = float(man["size"]) * float(man["tsamp"])
    best, n_matches = None, 0
    for c in candidates:
        f = _cand_field(c, "freq")
        if not _harmonically_related(f, f0, freq_tol, max_harm):
            continue
        dacc = abs(abs(_cand_field(c, "acc")) - abs(float(man["accel"])))
        if dacc * tobs / SPEED_OF_LIGHT > freq_tol:
            continue
        djerk = abs(abs(_cand_field(c, "jerk")) - abs(float(man["jerk"])))
        if djerk * tobs * tobs / (6.0 * SPEED_OF_LIGHT) > freq_tol:
            continue
        if dm_tol is not None and abs(
                _cand_field(c, "dm") - float(man["dm"])) > dm_tol:
            continue
        n_matches += 1
        if best is None or _cand_field(c, "snr") > _cand_field(best, "snr"):
            best = c
    return {
        "recovered": best is not None,
        "best": best,
        "best_snr": _cand_field(best, "snr") if best is not None else 0.0,
        "n_matches": n_matches,
    }


def smoke_observation(path: str, *, nsamps: int = 4096, nchans: int = 16,
                      seed: int = 0, truncate_bytes: int = 0,
                      noise_max: int = 32, amp: float = 60.0,
                      tsamp: float = 0.000256) -> dict:
    """The smoke tools' shared synthetic observation: a bright DM-0
    train pulsing every 16th sample over uniform noise (historically
    each tool's private ``_write_synthetic``).  Returns the manifest so
    smoke inputs double as injections.
    """
    return synthesize(path, period=16.0 * tsamp, duty=0.05, amp=amp,
                      noise_max=noise_max, nsamps=nsamps, nchans=nchans,
                      tsamp=tsamp, seed=seed, truncate_bytes=truncate_bytes)
