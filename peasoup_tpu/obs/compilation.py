"""Geometry-keyed XLA compile ledger (ISSUE 18).

ROADMAP item 3 names compile time — not search time — as the
production tail latency, but :func:`~.metrics.install_compile_hook`
collapses every backend compile into one global ``jit_compile``
timer.  This module adds the attribution side: a second
``jax.monitoring`` duration listener that stamps every backend
compile with the **program** it served (the explicitly-declared
compile context when a driver set one, else the innermost open trace
span), the **geometry fingerprint** of that program's shape key, and
the **device kind**, and persists the result as one JSON line in an
append-only ``compiles.jsonl`` stream (schema in
:mod:`.streams`; ingested by :func:`.warehouse.compile_rows`,
baselined by :func:`.baseline.compile_anomalies`).

With the ledger, three previously-invisible facts become queryable:

* cold vs warm dispatch — the first compile of a (program, geometry,
  device) key writes ``seen_before: false``, every later compile of
  the *same* key writes ``seen_before: true`` and increments the
  ``jit.recompiles_seen_geometry`` counter (the ``compile_storm``
  health rule's input);
* which geometry paid which compile — an escalated re-search or a
  ``scale_up`` worker cold start names its geometry fingerprint;
* whether the persistent compile cache engaged — ``kind:"cache"``
  records from :func:`record_cache_event` land in the same stream,
  as do ``kind:"profile"`` records naming sampled
  ``jax.profiler`` artifacts.

Like the event log, persistence must never kill a search: the file
handle opens lazily in append mode, an I/O failure disables the sink
for the rest of the run with a single plain warning, and the on-disk
size is bounded by ``.1`` rotation.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
import warnings
from contextlib import contextmanager

from .metrics import _BACKEND_COMPILE_EVENT, MetricsRegistry, REGISTRY
from .events import _json_safe
from .streams import stream_version

#: sourced from the stream catalog — cannot drift from the contract
COMPILES_VERSION = stream_version("compiles")

#: rotate the on-disk ledger past this size (one ``.1`` generation,
#: like events.jsonl and the telemetry shards)
DEFAULT_MAX_LEDGER_BYTES = 1024 * 1024


class CompileLedger:
    """Append-only JSONL sink for compile/cache/profile records.

    ``path`` may be empty: records are then counted into the metrics
    registry but not persisted — the no-I/O default for library use.
    One lock guards the lazily-opened line-buffered handle and the
    I/O-failure latch (a telemetry write must never raise into the
    dispatching thread that triggered the compile).
    """

    def __init__(self, path: str = "", *,
                 max_ledger_bytes: int = DEFAULT_MAX_LEDGER_BYTES,
                 clock=time.time):
        self.path = path or ""
        self.max_ledger_bytes = int(max_ledger_bytes)
        self._lock = threading.Lock()
        self._file = None
        self._io_failed = False
        self._clock = clock
        try:
            self._host = socket.gethostname()
        except OSError:
            self._host = ""

    def _maybe_rotate(self) -> None:
        """Rotate the live ledger to ``<path>.1`` past the byte budget.
        Caller holds the lock; errors are swallowed."""
        if self.max_ledger_bytes <= 0:
            return
        try:
            if os.path.getsize(self.path) < self.max_ledger_bytes:
                return
        except OSError:
            return  # no file yet
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
            self._file = None
        try:
            os.replace(self.path, self.path + ".1")
        except OSError:
            pass

    def record(self, kind: str, **fields) -> dict:
        """Append one typed ledger line; returns the record written."""
        rec = {
            "v": COMPILES_VERSION,
            "ts": round(self._clock(), 6),
            "host": self._host,
            "pid": os.getpid(),
            "kind": str(kind),
        }
        for key, value in fields.items():
            rec[key] = _json_safe(value)
        with self._lock:
            if self.path and not self._io_failed:
                try:
                    self._maybe_rotate()
                    if self._file is None:
                        d = os.path.dirname(self.path)
                        if d:
                            os.makedirs(d, exist_ok=True)
                        self._file = open(self.path, "a", buffering=1)
                    self._file.write(json.dumps(rec) + "\n")
                except OSError as exc:
                    self._io_failed = True
                    warnings.warn(
                        f"compile ledger {self.path!r} disabled: {exc}")
        return rec

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                finally:
                    self._file = None


_global_lock = threading.Lock()
_LEDGER = CompileLedger()


def get_compile_ledger() -> CompileLedger:
    return _LEDGER


def configure_compile_ledger(
        path: str, *,
        max_ledger_bytes: int = DEFAULT_MAX_LEDGER_BYTES
) -> CompileLedger:
    """Point the process-wide compile ledger at ``path`` (e.g. the
    CLI's ``<outdir>/compiles.jsonl`` or a worker's spool-level
    ledger).  Replaces the previous sink; already-written records are
    not rewritten."""
    global _LEDGER
    with _global_lock:
        _LEDGER.close()
        _LEDGER = CompileLedger(path, max_ledger_bytes=max_ledger_bytes)
        return _LEDGER


# -- compile attribution context --------------------------------------------

# The monitoring listener fires on the thread that dispatched the
# compile, but carries no payload beyond the duration — attribution
# comes from (a) the compile context a driver declared around its
# dispatches and (b) the innermost open trace span on that thread.
# One lock guards the context and the process seen-set.
_ctx_lock = threading.Lock()
_ctx_program = ""
_ctx_geometry: dict | None = None
_seen_keys: set = set()


def set_compile_context(program: str = "",
                        geometry: dict | None = None) -> tuple:
    """Declare which program/geometry subsequent compiles serve.

    Returns the previous ``(program, geometry)`` pair so callers can
    restore it; :func:`compile_context` is the scoped spelling.
    ``geometry`` is a small plain dict of shape-determining fields
    (what :func:`.warehouse.geometry_fingerprint` hashes)."""
    global _ctx_program, _ctx_geometry
    with _ctx_lock:
        prev = (_ctx_program, _ctx_geometry)
        _ctx_program = str(program or "")
        _ctx_geometry = dict(geometry) if geometry else None
        return prev


@contextmanager
def compile_context(program: str = "", geometry: dict | None = None):
    """Scoped :func:`set_compile_context` (restores on exit)."""
    prev = set_compile_context(program, geometry)
    try:
        yield
    finally:
        set_compile_context(prev[0], prev[1])


def _device_kind() -> str:
    try:
        import jax

        return str(jax.devices()[0].device_kind)
    except Exception:
        return ""


def _record_compile(duration_s: float, reg: MetricsRegistry) -> None:
    """Attribute one backend compile and append its ledger line."""
    span_name = ""
    try:
        from .trace import current_span_name

        span_name = current_span_name() or ""
    except Exception:
        pass
    with _ctx_lock:
        program = _ctx_program
        geometry = _ctx_geometry
    fingerprint = ""
    if geometry:
        try:
            from .warehouse import geometry_fingerprint

            fingerprint = geometry_fingerprint(geometry)
        except Exception:
            fingerprint = ""
    if not program:
        program = span_name
    kind = _device_kind()
    seen = False
    if program or fingerprint:
        key = (program, fingerprint, kind)
        with _ctx_lock:
            seen = key in _seen_keys
            _seen_keys.add(key)
    if program:
        reg.inc("jit.compiles_attributed")
    if seen:
        reg.inc("jit.recompiles_seen_geometry")
    get_compile_ledger().record(
        "compile",
        program=program,
        geometry=fingerprint,
        device_kind=kind,
        duration_s=round(float(duration_s), 6),
        seen_before=seen,
        span=span_name,
    )


_listener_lock = threading.Lock()
_listener_installed = False


def install_compile_ledger(
        registry: MetricsRegistry | None = None) -> bool:
    """Attribute every XLA backend compile into the ledger
    (idempotent; composes with the counting-only
    :func:`~.metrics.install_compile_hook`).  Returns True if the
    listener is active."""
    global _listener_installed
    reg = registry if registry is not None else REGISTRY
    with _listener_lock:
        if _listener_installed:
            return True
        try:
            from jax import monitoring

            def _on_duration(event, duration, **kwargs):
                if event == _BACKEND_COMPILE_EVENT:
                    _record_compile(float(duration), reg)

            monitoring.register_event_duration_secs_listener(
                _on_duration)
        except Exception:  # pragma: no cover - jax.monitoring absent
            return False
        _listener_installed = True
        return True


def reset_seen_geometries() -> None:
    """Forget the process seen-set (tests; a fresh cold-start probe)."""
    with _ctx_lock:
        _seen_keys.clear()


# -- cache / profile records -------------------------------------------------

def record_cache_event(enabled: bool, cache_dir: str = "",
                       registry: MetricsRegistry | None = None) -> dict:
    """Ledger a persistent-compile-cache engagement (or refusal).

    Called by ``utils.compilecache.enable_compile_cache`` so whether
    the cache actually engaged — and where — is a queryable fact
    instead of an invisible return value."""
    reg = registry if registry is not None else REGISTRY
    if enabled:
        reg.inc("compile_cache.enabled")
    return get_compile_ledger().record(
        "cache", enabled=bool(enabled), dir=str(cache_dir or ""))


def record_profile(path: str,
                   registry: MetricsRegistry | None = None) -> dict:
    """Ledger one sampled ``jax.profiler`` capture artifact."""
    reg = registry if registry is not None else REGISTRY
    reg.inc("profile.captures")
    return get_compile_ledger().record("profile", path=str(path))


# -- readers ------------------------------------------------------------------

def read_compiles(path: str, kinds=None) -> list[dict]:
    """Torn-line-tolerant reader for a ``compiles.jsonl`` ledger.

    Skips unparseable lines and records from a future schema version;
    ``kinds`` filters on the record kind."""
    out: list[dict] = []
    try:
        fh = open(path)
    except OSError:
        return out
    with fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict):
                continue
            if int(rec.get("v", 0) or 0) > COMPILES_VERSION:
                continue
            if kinds is not None and rec.get("kind") not in kinds:
                continue
            out.append(rec)
    return out


def summarize_compiles(records: list[dict]) -> list[dict]:
    """Aggregate compile records per (program, geometry, device kind).

    Returns one row per key — compile count, recompile count (lines
    with ``seen_before``), total/max seconds — sorted by total compile
    seconds descending, so ``obs compiles`` surfaces the most
    expensive program first."""
    agg: dict[tuple, dict] = {}
    for rec in records:
        if rec.get("kind") != "compile":
            continue
        key = (str(rec.get("program") or ""),
               str(rec.get("geometry") or ""),
               str(rec.get("device_kind") or ""))
        row = agg.setdefault(key, {
            "program": key[0], "geometry": key[1],
            "device_kind": key[2], "compiles": 0, "recompiles": 0,
            "total_s": 0.0, "max_s": 0.0,
        })
        row["compiles"] += 1
        if rec.get("seen_before"):
            row["recompiles"] += 1
        dur = float(rec.get("duration_s") or 0.0)
        row["total_s"] += dur
        row["max_s"] = max(row["max_s"], dur)
    rows = sorted(agg.values(),
                  key=lambda r: r["total_s"], reverse=True)
    for row in rows:
        row["total_s"] = round(row["total_s"], 6)
        row["max_s"] = round(row["max_s"], 6)
    return rows
