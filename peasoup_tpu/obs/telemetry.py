"""Live per-host telemetry time-series for the survey fleet.

The service layer's observability so far is post-hoc and per-run:
``run_report.json``, span traces, one ``fleet/<host>.json`` status
snapshot per drain.  This module is the continuous complement — the
always-on, low-overhead sampling layer (Dapper-style) that the health
plane (``serve/health.py``) and ``status --watch`` read from:

* :class:`TelemetrySampler` — a daemon thread (``Event.wait`` cadence,
  PSL008-clean, same shape as the spool's ``LeaseHeartbeat``) that a
  worker runs for the duration of a drain.  Every tick it appends one
  schema-versioned JSON line to a **per-host single-writer shard**
  ``fleet/ts-<host>.jsonl``: counter/timer *deltas* since the previous
  tick (via :class:`~.metrics.MetricsCursor`, so samples are
  per-interval rates, not process-lifetime totals), current gauges
  (HBM high-water, ``scheduler.jobs_per_hour``, batch fill), plus
  whatever the owner injects through ``extras`` (queue depths from the
  spool — the sampler itself never imports ``serve/``, keeping the
  obs→serve layering one-way).
* a merged, torn-tail-tolerant reader: :func:`read_samples` /
  :func:`latest_by_host` merge every host's shard (plus its rotated
  ``.1`` generation), skip corrupt/torn lines, and sort by sample
  timestamp so cross-host clock skew degrades ordering gracefully
  instead of crashing the health evaluation.

Sample line schema (one JSON object per line)::

    {"v": 1, "ts": <unix s>, "host": "<label>", "pid": <int>,
     "seq": <per-process monotonic>, "interval_s": <cadence>,
     "counters": {<name>: <delta>}, "timers": {<name>:
         {"count": <d>, "host_s": <d>, "device_s": <d>}},
     "gauges": {<name>: <value>}, "overhead_s": <cumulative sampler
     cost>, ...extras (e.g. "queue": {...})}

Shard rotation is bounded: when the live shard exceeds
``max_shard_bytes`` it is renamed to ``ts-<host>.jsonl.1`` (replacing
the previous generation), so a long-lived host holds at most two
generations on disk.  The reader merges both.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time

from .metrics import REGISTRY, MetricsCursor

#: sample-line schema version
TS_SCHEMA_VERSION = 1

#: default sampling cadence (seconds)
DEFAULT_INTERVAL_S = 5.0

#: rotate the live shard past this size; one old generation is kept
DEFAULT_MAX_SHARD_BYTES = 4 * 1024 * 1024

_SHARD_RE = re.compile(r"^ts-(?P<host>[A-Za-z0-9_.-]+)\.jsonl$")


def safe_host(label: str) -> str:
    """Sanitise a host label for use in a shard filename."""
    cleaned = re.sub(r"[^A-Za-z0-9_.-]+", "_", str(label).strip())
    return cleaned or "host"


def shard_path(ts_dir: str, host: str) -> str:
    """The single-writer time-series shard for ``host`` under
    ``ts_dir`` (normally the spool's ``fleet/`` directory)."""
    return os.path.join(ts_dir, f"ts-{safe_host(host)}.jsonl")


class TelemetrySampler:
    """Appends one telemetry sample per interval to a per-host shard.

    Single-writer by construction: each host writes only its own
    ``ts-<host>.jsonl``, so no cross-host locking exists anywhere in
    the plane.  ``start()`` emits an immediate first sample and
    ``stop()`` a final one, so even a drain shorter than one interval
    leaves a usable time-series behind.

    ``extras`` is an optional zero-arg callable returning a dict merged
    into every sample (the worker passes queue depths; the sampler
    deliberately knows nothing about spools).  An ``extras`` failure is
    recorded in the sample (``"extras_error"``) rather than raised —
    telemetry must never kill a drain.

    The cumulative cost of sampling itself is tracked in
    ``overhead_s`` and written into every sample, so "is the sampler
    cheap enough" is answerable from the data it produces.
    """

    def __init__(self, path: str, host: str,
                 interval_s: float = DEFAULT_INTERVAL_S, *,
                 registry=None, extras=None,
                 max_shard_bytes: int = DEFAULT_MAX_SHARD_BYTES,
                 clock=time.time):
        self.path = str(path)
        self.host = safe_host(host)
        self.interval_s = max(0.05, float(interval_s))
        self._registry = registry if registry is not None else REGISTRY
        self._extras = extras
        self.max_shard_bytes = int(max_shard_bytes)
        self._clock = clock
        self._cursor = MetricsCursor()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self._seq = 0
        self._io_failed = False
        self.samples_written = 0
        self.overhead_s = 0.0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "TelemetrySampler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self.sample_now()
        self._thread = threading.Thread(
            target=self._run, name=f"telemetry-{self.host}", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None
        self.sample_now()

    def __enter__(self) -> "TelemetrySampler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.sample_now()

    # -- sampling ----------------------------------------------------------

    def sample_now(self) -> dict:
        """Compose and append one sample; returns the record."""
        t0 = time.perf_counter()
        with self._lock:
            self._seq += 1
            snap = self._registry.snapshot(self._cursor)
            deltas = snap.get("deltas", {"counters": {}, "timers": {}})
            rec = {
                "v": TS_SCHEMA_VERSION,
                "ts": round(self._clock(), 6),
                "host": self.host,
                "pid": os.getpid(),
                "seq": self._seq,
                "interval_s": self.interval_s,
                "counters": deltas.get("counters", {}),
                "timers": deltas.get("timers", {}),
                "gauges": snap.get("gauges", {}),
            }
            if self._extras is not None:
                try:
                    ext = self._extras()
                    if isinstance(ext, dict):
                        for k, v in ext.items():
                            rec.setdefault(str(k), v)
                except Exception as exc:
                    rec["extras_error"] = repr(exc)
            rec["overhead_s"] = round(
                self.overhead_s + (time.perf_counter() - t0), 6)
            self._append(rec)
            self.overhead_s += time.perf_counter() - t0
        return rec

    def _append(self, rec: dict) -> None:
        if self._io_failed:
            return
        try:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._maybe_rotate()
            with open(self.path, "a", buffering=1) as fh:
                fh.write(json.dumps(rec) + "\n")
            self.samples_written += 1
        except OSError:
            # disk trouble must not kill the drain; one-way latch so a
            # wedged filesystem costs one syscall per tick at most
            self._io_failed = True

    def _maybe_rotate(self) -> None:
        try:
            if os.path.getsize(self.path) >= self.max_shard_bytes:
                os.replace(self.path, self.path + ".1")
        except OSError:
            pass


# -- merged reader ---------------------------------------------------------


def _read_shard_lines(path: str) -> list[dict]:
    """Parse one shard, skipping torn/corrupt lines (a sampler killed
    mid-write leaves a torn tail; that must never poison the merge)."""
    out: list[dict] = []
    try:
        with open(path, "r", errors="replace") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except (json.JSONDecodeError, ValueError):
                    continue
                if isinstance(rec, dict) and "ts" in rec:
                    out.append(rec)
    except OSError:
        pass
    return out


def shard_hosts(ts_dir: str) -> list[str]:
    """Host labels that have a time-series shard under ``ts_dir``."""
    hosts = set()
    try:
        names = os.listdir(ts_dir)
    except OSError:
        return []
    for name in names:
        base = name[:-2] if name.endswith(".1") else name
        m = _SHARD_RE.match(base)
        if m:
            hosts.add(m.group("host"))
    return sorted(hosts)


def read_samples(ts_dir: str, hosts=None, since: float | None = None
                 ) -> list[dict]:
    """Merge every host's shard (rotated ``.1`` generation first, then
    live) into one list sorted by sample timestamp.

    Cross-host clock skew means the merged order is only as good as
    the hosts' clocks — the sort is stable and per-host order is
    preserved by ``seq``, so downstream trend rules should group by
    ``host`` before differencing.  ``since`` drops samples older than
    the given unix timestamp after the merge.
    """
    wanted = None if hosts is None else {safe_host(h) for h in hosts}
    merged: list[dict] = []
    for host in shard_hosts(ts_dir):
        if wanted is not None and host not in wanted:
            continue
        live = shard_path(ts_dir, host)
        for path in (live + ".1", live):
            for rec in _read_shard_lines(path):
                rec.setdefault("host", host)
                merged.append(rec)
    if since is not None:
        merged = [r for r in merged if r.get("ts", 0) >= since]
    merged.sort(key=lambda r: r.get("ts", 0))
    return merged


def latest_by_host(ts_dir: str) -> dict[str, dict]:
    """Most recent sample per host (by that host's own clock)."""
    out: dict[str, dict] = {}
    for rec in read_samples(ts_dir):
        host = rec.get("host", "")
        prev = out.get(host)
        if prev is None or rec.get("ts", 0) >= prev.get("ts", 0):
            out[host] = rec
    return out
