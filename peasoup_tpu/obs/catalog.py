"""The metrics catalog: every counter/gauge name, in one place.

The warehouse schema, the health rules and any dashboard built on the
telemetry shards all join on metric *names* — a name emitted in code
but absent here is a dangling wire nobody will ever query.  Lint rule
PSL009 therefore requires every literal ``METRICS.inc("...")`` /
``METRICS.gauge("...")`` name in the tree to appear in
:data:`CATALOG` (or match a documented dynamic prefix in
:data:`DYNAMIC_PREFIXES`), so adding a metric *forces* cataloguing
it.

This module is pure data — import it from anywhere, it imports
nothing from the package.
"""

from __future__ import annotations

#: every literal metric name in the tree -> one-line description.
#: Counters unless marked (gauge).
CATALOG: dict[str, str] = {
    # -- canary / checkpoint ------------------------------------------------
    "canary.missed": "injected canary pulsar NOT recovered this run",
    "canary.recovered": "injected canary pulsar recovered this run",
    "checkpoint.resumes": "searches resumed from a checkpoint",
    "checkpoint.rows_resumed": "DM rows skipped thanks to a resume",
    # -- chunk planner (gauges) --------------------------------------------
    "chunk.accel_block": "(gauge) planned acceleration-block size",
    "chunk.compact_k": "(gauge) planned peak-compaction capacity K",
    "chunk.dm_chunk": "(gauge) planned DM-chunk height",
    "chunk.peak_capacity": "(gauge) planned per-trial peak capacity",
    "chunk.pipeline_depth": "(gauge) planned upload pipeline depth",
    # -- cold start / compile cache -----------------------------------------
    "coldstart.cold_to_first_candidate_s": "(gauge) wall seconds "
                                           "from drain start to the "
                                           "first completed job",
    "compile_cache.enabled": "persistent XLA compile-cache "
                             "engagements this process",
    # -- device -------------------------------------------------------------
    "device_duty_cycle": "(gauge) device seconds per wall second "
                         "over the last drain window",
    # -- events plane -------------------------------------------------------
    "events.flood_suppressed": "event-log lines dropped by flood "
                               "control",
    # -- fold ---------------------------------------------------------------
    "fold.cache_evicted": "fold plan-cache evictions",
    # -- HBM accounting (gauges) -------------------------------------------
    "hbm.budget_bytes": "(gauge) planner's HBM budget",
    "hbm.data_bytes": "(gauge) staged observation bytes on device",
    "hbm.est_full_bytes": "(gauge) planner's full-problem estimate",
    "hbm.high_water_bytes": "(gauge) max bytes_in_use seen at any "
                            "span close",
    "hbm.probed_fold_samp_bytes": "(gauge) measured fold bytes per "
                                  "sample (memory_analysis probe)",
    "hbm.probed_row_bytes": "(gauge) measured trial bytes per DM row "
                            "(memory_analysis probe)",
    "hbm.probed_spectrum_bytes": "(gauge) measured bytes per live "
                                 "accel spectrum element "
                                 "(memory_analysis probe)",
    # -- injection / parity (gauges) ---------------------------------------
    "injection.recovered": "(gauge) 1.0 when the parity injection "
                           "was recovered",
    "injection.snr_interbin": "(gauge) parity injection interbin SNR",
    "injection.snr_peak": "(gauge) parity injection peak SNR",
    "injection.snr_whiten": "(gauge) parity injection whitened SNR",
    # -- jit ----------------------------------------------------------------
    "jit.backend_compiles": "XLA backend_compile events this process",
    "jit.compiles_attributed": "backend compiles attributed to a "
                               "(program, geometry) key in the "
                               "compile ledger",
    "jit.recompiles_seen_geometry": "backend compiles on an "
                                    "already-seen (program, "
                                    "geometry, device) key",
    # -- lineage (candidate provenance) -------------------------------------
    "lineage.mark_errors": "lineage decision marks that failed to "
                           "write",
    "lineage.marks": "candidate selection-decision marks written to "
                     "lineage.jsonl",
    # -- peaks / runs -------------------------------------------------------
    "peaks.compact_pallas": "pallas threshold-compaction dispatches",
    "runs.fused_fold_dispatches": "batched fold program dispatches",
    "runs.host_loop": "searches run on the host-loop path",
    "runs.mesh_chunked": "searches run on the chunked mesh path",
    "runs.mesh_fused": "searches run on the fused mesh path",
    "runs.mesh_fused_batched": "searches run on the batched fused "
                               "path",
    # -- profiler -----------------------------------------------------------
    "profile.captures": "sampled jax.profiler captures written",
    # -- scheduler ----------------------------------------------------------
    "scheduler.admission_deferred": "submits deferred by a token "
                                    "bucket",
    "scheduler.admission_rejected": "submits rejected by admission "
                                    "control",
    "scheduler.batch_fill": "jobs packed into batched dispatches",
    "scheduler.batched_dispatches": "multi-observation batched "
                                    "dispatches",
    "scheduler.claimed": "jobs claimed from pending/",
    "scheduler.exhausted": "jobs failed past max attempts",
    "scheduler.geometry_trimmed": "batch claims trimmed on geometry "
                                  "mismatch",
    "scheduler.heartbeats": "lease heartbeats written",
    "scheduler.jobs_per_hour": "(gauge) live drain throughput",
    "scheduler.lease_reaped": "expired leases reaped back to "
                              "pending/",
    "scheduler.plan_reuse": "search-plan cache hits across jobs",
    "scheduler.prefetch_hits": "claims served from the prefetcher",
    "scheduler.prefetch_misses": "claims that missed the prefetcher",
    "scheduler.quarantined": "jobs quarantined on poison input",
    "scheduler.requeued": "jobs requeued for another attempt",
    "scheduler.retried": "job attempts after the first",
    "scheduler.staged_raw_hits": "device-staged uploads reused on "
                                 "claim",
    "scheduler.staged_raw_uploads": "raw observations staged to "
                                    "device ahead of claim",
    "scheduler.submitted": "jobs accepted into pending/",
    "scheduler.succeeded": "jobs completed into done/",
    "scheduler.timeout_abandoned": "jobs abandoned on wall-clock "
                                   "timeout",
    # -- search geometry (gauges) ------------------------------------------
    "search.batch": "(gauge) observations per batched dispatch",
    "search.fft_size": "(gauge) padded FFT size of the run",
    "search.n_devices": "(gauge) devices the run sharded over",
    "search.n_dm_trials": "(gauge) DM trials of the run",
    # -- survey store (ISSUE 20) --------------------------------------------
    "store.compactions": "shard tails folded into sealed segments",
    "store.compacted_records": "records sealed into segments",
    "store.query_requests": "query-service requests answered",
    # -- supervisor ---------------------------------------------------------
    "supervisor.actions": "supervisor actions executed",
    "supervisor.throttled": "supervisor actions skipped by the "
                            "rate budget",
    # -- timeline / trace ---------------------------------------------------
    "timeline.mark_errors": "timeline marks that failed to write",
    "timeline.marks": "timeline marks written",
    "timeline.marks_dropped": "timeline marks dropped by flood "
                              "control",
    "trace.listener_errors": "span listeners dropped after raising",
    "trace.spans_dropped": "spans dropped past the retention cap",
}

#: metric families whose names are built dynamically (f-strings) —
#: PSL009 cannot check these literally, so the *prefix* is the
#: catalogued contract
DYNAMIC_PREFIXES: tuple = (
    "events.",                    # events.<kind> per warn_event kind
    "peaks.method_",              # peaks.method_<sort|two_stage|...>
    "scheduler.prefetch_miss.",   # scheduler.prefetch_miss.<class>
    "supervisor.action.",         # supervisor.action.<action name>
)


def is_cataloged(name: str) -> bool:
    """True when ``name`` is in the catalog or matches a documented
    dynamic prefix (what lint rule PSL009 enforces)."""
    return name in CATALOG or any(
        name.startswith(p) for p in DYNAMIC_PREFIXES)
