"""End-of-run machine-readable ``run_report.json``.

The reference's only run artefact is ``overview.xml`` with a
wall-clock ``<execution_times>`` block; a production service needs a
machine-readable report it can ship to a metrics backend without an
XML parser.  :func:`build_run_report` assembles one dict from the
process-wide telemetry (metrics registry + event log) plus the
``SearchResult``; the CLI writes it as ``run_report.json`` next to
``overview.xml`` (and mirrors a ``<telemetry>`` section into the XML
for the legacy toolchain).

Report schema (top-level keys, all optional consumers should
tolerate additions)::

    schema_version   int    report schema version (2: adds `perf`)
    version          int    legacy alias of schema_version
    generated_utc    str    ISO-8601 UTC timestamp
    timers           {name: seconds}        driver wall-clock timers
    stage_timers     {name: {count, host_s, device_s}}
    counters         {name: int}            incl. events.<kind> tallies
    gauges           {name: float}          incl. hbm.* figures
    spans            {name: {count, total_s, self_s, device_s}}
                     span-trace table (obs/trace.py), self-time ordered
    events           {kind: count}          event-log summary
    jit              {backend_compiles, compile_s, programs: {name: n}}
    device           {backend, jax_version, device_count, devices: []}
    perf             per-stage cost model x measured device time
                     (obs/costmodel.py): {peak, geometry, stages:
                     {name: {flops, bytes_read, bytes_written,
                     dominant, intensity_flops_per_byte, [device_s,
                     basis, attribution, achieved_flops_per_s,
                     achieved_bytes_per_s, utilization]}}, total}.
                     The bracketed keys are OMITTED (never null) when
                     no cost data or stage seconds exist — e.g. a
                     bare-telemetry report with no search run.
    memory           measured HBM footprints (obs/memprof.py):
                     {closure_factor, [programs: [{program,
                     model_bytes, measured, measured_bytes, ratio,
                     ok}]], [watermark], [probed_coefficients]}.
                     OMITTED entirely until a memory_analysis probe
                     ran this process or the backend reports live
                     memory stats — probing is explicit (obs memory
                     --probe, bench), never per-job.
    candidates       {count, folded, best_snr, best_folded_snr, ...}
    config           {key search parameters}
"""

from __future__ import annotations

import json
import os
import time

from ..utils.atomicio import atomic_write_json

REPORT_VERSION = 2


def device_summary() -> dict:
    """Backend + per-device identity (TPU stand-in for the reference's
    cuda_device_parameters, mirroring xml_writer.add_device_info)."""
    try:
        import jax

        devices = jax.devices()
        return {
            "backend": jax.default_backend(),
            "jax_version": jax.__version__,
            "device_count": len(devices),
            "devices": [
                {"id": ii, "kind": str(d.device_kind),
                 "platform": str(d.platform)}
                for ii, d in enumerate(devices)
            ],
        }
    except Exception as exc:  # pragma: no cover - jax init failure
        return {"error": repr(exc)}


def candidate_summary(candidates) -> dict:
    """Aggregate candidate statistics (counts, SNR extremes, DM/freq
    coverage) — the per-run health signal a survey dashboard plots."""
    cands = list(candidates)
    out: dict = {"count": len(cands)}
    if not cands:
        return out
    snrs = [float(c.snr) for c in cands]
    folded = [c for c in cands if float(c.folded_snr) > 0.0]
    out.update(
        folded=len(folded),
        best_snr=round(max(snrs), 4),
        median_snr=round(sorted(snrs)[len(snrs) // 2], 4),
        n_assoc_total=sum(c.count_assoc() for c in cands),
        dm_min=round(min(float(c.dm) for c in cands), 6),
        dm_max=round(max(float(c.dm) for c in cands), 6),
        freq_min_hz=round(min(float(c.freq) for c in cands), 6),
        freq_max_hz=round(max(float(c.freq) for c in cands), 6),
    )
    if folded:
        out["best_folded_snr"] = round(
            max(float(c.folded_snr) for c in folded), 4)
    return out


_CONFIG_KEYS = (
    "infilename", "dm_start", "dm_end", "dm_tol", "acc_start", "acc_end",
    "acc_tol", "nharmonics", "npdmp", "min_snr", "limit", "peak_capacity",
    "compact_capacity", "hbm_budget_gb", "dm_chunk", "accel_block",
    "trial_nbits", "subband_dedisp", "size",
)


def build_run_report(result=None, registry=None, events=None,
                     extra: dict | None = None) -> dict:
    """Assemble the run report dict.

    ``result``: a ``SearchResult`` (or None for a bare-telemetry
    report); ``registry``/``events`` default to the process-wide
    instances.  ``extra`` is merged in last under its own keys — the
    benchmark uses it for its parity/vs_baseline figures.
    """
    from .events import get_event_log
    from .metrics import REGISTRY, jit_program_cache_sizes

    reg = registry if registry is not None else REGISTRY
    log = events if events is not None else get_event_log()
    snap = reg.snapshot()
    jit_timer = snap["timers"].get("jit_compile", {})
    report = {
        "schema_version": REPORT_VERSION,
        "version": REPORT_VERSION,
        "generated_utc": time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "timers": {},
        "stage_timers": {
            k: {"count": v["count"],
                "host_s": round(v["host_s"], 6),
                "device_s": round(v["device_s"], 6)}
            for k, v in snap["timers"].items()
        },
        "counters": snap["counters"],
        "gauges": snap["gauges"],
        "spans": {},
        "events": log.summary(),
        "jit": {
            "backend_compiles": snap["counters"].get(
                "jit.backend_compiles", 0),
            "compile_s": round(jit_timer.get("host_s", 0.0), 4),
            "programs": jit_program_cache_sizes(),
        },
        "device": device_summary(),
    }
    try:
        from .trace import span_table

        report["spans"] = span_table()
    except Exception:  # pragma: no cover - tracing must never kill a run
        pass
    try:
        from .costmodel import get_run_costs, perf_section

        run_costs = get_run_costs()
        if run_costs is not None:
            # absent cost data (no search ran this process — e.g. the
            # coincidencer, or a bare-telemetry report) simply omits
            # the section; consumers never see nulls
            report["perf"] = perf_section(
                run_costs, report["stage_timers"], report["device"],
                snap["gauges"])
    except Exception:  # pragma: no cover - perf must never kill a run
        pass
    try:
        from .memprof import memory_report

        # probe=False: only what is already known (cached program
        # footprints + the live watermark) — a per-job report must not
        # compile five programs; explicit probing is `obs memory
        # --probe` / bench / tests
        mem = memory_report(probe=False)
        if mem.get("programs") or mem.get("watermark"):
            report["memory"] = mem
    except Exception:  # pragma: no cover - memprof must never kill a run
        pass
    if result is not None:
        report["timers"] = {
            k: round(float(v), 6)
            for k, v in getattr(result, "timers", {}).items()
            if isinstance(v, (int, float))
        }
        report["candidates"] = candidate_summary(result.candidates)
        cfg = getattr(result, "config", None)
        if cfg is not None:
            report["config"] = {
                k: getattr(cfg, k)
                for k in _CONFIG_KEYS if hasattr(cfg, k)
            }
        report["n_dm_trials"] = int(len(result.dm_list))
        report["n_accel_trials_dm0"] = int(len(result.acc_list_dm0))
    if extra:
        report.update(extra)
    return report


def write_run_report(path: str, result=None, registry=None, events=None,
                     extra: dict | None = None) -> dict:
    """Build and atomically write ``run_report.json``; returns the
    report dict (telemetry I/O failures warn, never raise)."""
    report = build_run_report(result, registry, events, extra)
    try:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        atomic_write_json(path, report, indent=1, sort_keys=True,
                          trailing_newline=True)
    except OSError as exc:
        import warnings

        warnings.warn(f"could not write run report {path!r}: {exc}")
    return report


def format_stage_table(report: dict) -> str:
    """Human-readable per-stage timing table (CLI ``--verbose``).

    Renders the registry's stage timers — host wall-clock next to the
    device share — then the event summary, so a terminal user sees
    what the XML/JSON consumers see without opening either.
    """
    lines = ["stage                          n   host_s  device_s"]
    stages = report.get("stage_timers", {})
    for name in sorted(stages, key=lambda k: -stages[k]["host_s"]):
        rec = stages[name]
        lines.append(
            f"{name:<28}{rec['count']:>4} {rec['host_s']:>8.3f} "
            f"{rec['device_s']:>9.3f}"
        )
    perf = report.get("perf")
    if perf:
        peak = perf.get("peak", {})
        lines.append(
            f"perf vs {peak.get('kind', '?')} x"
            f"{peak.get('n_devices', 1)} "
            f"({peak.get('flops_per_s', 0) / 1e12:.1f} TFLOP/s, "
            f"{peak.get('bytes_per_s', 0) / 1e9:.0f} GB/s"
            f"{'' if peak.get('matched') else ', unmatched kind'}):")
        lines.append(
            "stage          Gflop    GB  intens  achieved    util")
        for name, row in perf.get("stages", {}).items():
            ach = row.get("achieved_flops_per_s")
            util = row.get("utilization")
            gb = (row.get("bytes_read", 0)
                  + row.get("bytes_written", 0)) / 1e9
            lines.append(
                f"{name:<12}{row.get('flops', 0) / 1e9:>8.2f}"
                f"{gb:>6.2f}"
                f"{row.get('intensity_flops_per_byte', 0.0):>8.2f}"
                + (f"{ach / 1e9:>8.1f}G" if ach is not None
                   else f"{'-':>9}")
                + (f"{100 * util:>7.2f}%" if util is not None
                   else f"{'-':>8}")
            )
    jit = report.get("jit", {})
    if jit:
        lines.append(
            f"jit: {jit.get('backend_compiles', 0)} backend compiles, "
            f"{jit.get('compile_s', 0.0):.2f} s"
        )
    ev = report.get("events", {})
    if ev:
        lines.append("events: " + ", ".join(
            f"{k}={v}" for k, v in sorted(ev.items())))
    return "\n".join(lines)
