"""Structured JSONL event log + the ``warn_event`` warning bridge.

The seed pipeline had ~15 ``warnings.warn`` call sites whose signals
(peak-buffer overflows, capacity escalations, checkpoint
invalidations, fold-domain skips, chunked-path fallbacks) vanished
into stderr.  Every one of those sites now calls :func:`warn_event`,
which

1. records a typed event — one JSON object per line in the configured
   ``events.jsonl`` (schema below), and
2. increments the ``events.<kind>`` counter in the metrics registry
   (so ``run_report.json``'s event summary matches the warnings
   raised), and
3. raises the exact same Python warning as before, so ``-W error``,
   ``pytest.warns`` and log scrapers keep working unchanged.

Line schema (one JSON object per line)::

    {"v": 1, "ts": <unix seconds>, "kind": "<snake_case type>",
     "message": "<human-readable>", "data": {<typed fields>}}

``data`` carries the machine-readable fields (dm trial index, counts,
capacities, paths) so a service can alert on them without parsing
message strings.  A repo lint test asserts no bare ``warnings.warn``
remains under ``peasoup_tpu/search/`` or ``peasoup_tpu/parallel/``.

Flood suppression: a wedged worker re-raising the same warning in a
tight loop must not grow the event log unboundedly.  Per event kind,
at most :data:`FLOOD_LIMIT` lines are persisted per
:data:`FLOOD_WINDOW_S`-second window; further repeats are *counted*
but not written, and when the window rolls over one ``event_flood``
summary line records how many were collapsed (``data.kind``,
``data.suppressed``).  Counters (``events.<kind>``), the in-memory
summary and the raised Python warnings are NEVER suppressed — only
the on-disk line volume is bounded.
"""

from __future__ import annotations

import json
import os
import threading
import time
import warnings

from .metrics import REGISTRY

SCHEMA_VERSION = 1

#: per-kind persisted-line budget per flood window (events beyond it
#: are counted, collapsed into one ``event_flood`` summary line)
FLOOD_LIMIT = 20

#: flood-window length in seconds
FLOOD_WINDOW_S = 60.0

#: rotate the on-disk log past this size (ISSUE 16): the live file is
#: renamed to ``<path>.1`` (dropping any previous generation) —
#: the same bounded-disk scheme as the ``ts-<host>.jsonl`` telemetry
#: shards and the warehouse segments
DEFAULT_MAX_LOG_BYTES = 1024 * 1024


def _json_safe(value):
    """Best-effort conversion of numpy scalars/arrays and misc objects
    into plain JSON types (events must never crash the search)."""
    try:
        json.dumps(value)
        return value
    except (TypeError, ValueError):
        pass
    tolist = getattr(value, "tolist", None)
    if callable(tolist):
        try:
            return tolist()
        except Exception:
            pass
    return repr(value)


class EventLog:
    """Append-only JSONL event sink with in-memory per-kind counts.

    ``path`` may be empty: events are then counted (registry + local
    summary) but not persisted — the no-I/O default for library use.
    The file handle opens lazily on first emit and is line-buffered;
    an I/O failure disables persistence for the rest of the run with a
    single plain warning (never an exception: telemetry must not kill
    a multi-hour search).
    """

    def __init__(self, path: str = "", registry=None, *,
                 flood_limit: int = FLOOD_LIMIT,
                 flood_window_s: float = FLOOD_WINDOW_S,
                 max_log_bytes: int = DEFAULT_MAX_LOG_BYTES,
                 clock=time.time):
        self.path = path or ""
        self.max_log_bytes = int(max_log_bytes)
        self._registry = registry if registry is not None else REGISTRY
        self._lock = threading.Lock()
        self._file = None
        self._counts: dict[str, int] = {}
        self._io_failed = False
        self.flood_limit = max(1, int(flood_limit))
        self.flood_window_s = float(flood_window_s)
        self._clock = clock
        # kind -> {"start": window open time, "written": lines
        # persisted this window, "suppressed": lines collapsed}
        self._flood: dict[str, dict] = {}

    def _flood_admit(self, kind: str, now: float) -> tuple[bool, dict | None]:
        """(persist this line?, flood-summary record to write first).

        Per-kind sliding window: the first ``flood_limit`` lines of a
        window persist; later repeats are counted.  A window rollover
        with suppressions pending emits ONE ``event_flood`` summary
        (kind/suppressed/window) so the log states what was dropped.
        Caller holds the lock.
        """
        st = self._flood.setdefault(
            kind, {"start": now, "written": 0, "suppressed": 0})
        summary = None
        if now - st["start"] >= self.flood_window_s:
            if st["suppressed"]:
                summary = self._flood_summary(kind, st, now)
            st["start"] = now
            st["written"] = 0
            st["suppressed"] = 0
        if st["written"] < self.flood_limit:
            st["written"] += 1
            return True, summary
        st["suppressed"] += 1
        self._registry.inc("events.flood_suppressed")
        return False, summary

    def _flood_summary(self, kind: str, st: dict, now: float) -> dict:
        return {
            "v": SCHEMA_VERSION,
            "ts": round(now, 6),
            "kind": "event_flood",
            "message": (f"collapsed {st['suppressed']} repeated "
                        f"{kind!r} event(s) in "
                        f"{self.flood_window_s:.0f}s window"),
            "data": {"kind": kind, "suppressed": st["suppressed"],
                     "window_s": self.flood_window_s},
        }

    def _maybe_rotate(self) -> None:
        """Rotate the live log to ``<path>.1`` past the byte budget
        (one retained generation, like the telemetry shards) so a
        long-lived worker bounds its per-job/event disk footprint.
        Caller holds the lock; errors are swallowed (a stat race must
        not kill the emitting run)."""
        if self.max_log_bytes <= 0:
            return
        try:
            if os.path.getsize(self.path) < self.max_log_bytes:
                return
        except OSError:
            return  # no file yet
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
            self._file = None
        try:
            os.replace(self.path, self.path + ".1")
        except OSError:
            pass

    def emit(self, kind: str, message: str = "", **fields) -> dict:
        """Record one typed event; returns the record written."""
        kind = str(kind)
        now = self._clock()
        rec = {
            "v": SCHEMA_VERSION,
            "ts": round(now, 6),
            "kind": kind,
            "message": str(message),
        }
        if fields:
            rec["data"] = {k: _json_safe(v) for k, v in fields.items()}
        with self._lock:
            self._counts[kind] = self._counts.get(kind, 0) + 1
            persist, summary = self._flood_admit(kind, now)
            if self.path and not self._io_failed:
                try:
                    self._maybe_rotate()
                    if self._file is None:
                        d = os.path.dirname(self.path)
                        if d:
                            os.makedirs(d, exist_ok=True)
                        self._file = open(self.path, "a", buffering=1)
                    if summary is not None:
                        self._file.write(json.dumps(summary) + "\n")
                    if persist:
                        self._file.write(json.dumps(rec) + "\n")
                except OSError as exc:
                    self._io_failed = True
                    warnings.warn(
                        f"event log {self.path!r} disabled: {exc}")
        self._registry.inc(f"events.{kind}")
        return rec

    def summary(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    # flush pending flood summaries so a bounded log
                    # still states exactly what it dropped
                    now = self._clock()
                    for kind, st in self._flood.items():
                        if st["suppressed"]:
                            self._file.write(json.dumps(
                                self._flood_summary(kind, st, now))
                                + "\n")
                            st["suppressed"] = 0
                    self._file.close()
                except OSError:
                    pass
                finally:
                    self._file = None


_global_lock = threading.Lock()
_LOG = EventLog()


def get_event_log() -> EventLog:
    return _LOG


def configure_event_log(path: str, *,
                        max_log_bytes: int = DEFAULT_MAX_LOG_BYTES
                        ) -> EventLog:
    """Point the process-wide event log at ``path`` (e.g. the CLI's
    ``<outdir>/events.jsonl``).  Replaces the previous sink; already-
    emitted events are not rewritten.  The file is created immediately
    (even if no event ever fires) so "clean run" and "no log
    configured" are distinguishable artefacts.  ``max_log_bytes``
    bounds the on-disk size via ``.1`` rotation (0 disables)."""
    global _LOG
    with _global_lock:
        _LOG.close()
        _LOG = EventLog(path, max_log_bytes=max_log_bytes)
        if path:
            try:
                d = os.path.dirname(path)
                if d:
                    os.makedirs(d, exist_ok=True)
                open(path, "a").close()
            except OSError as exc:
                warnings.warn(f"event log {path!r} not writable: {exc}")
        return _LOG


def warn_event(kind: str, message: str, *, category=UserWarning,
               stacklevel: int = 3, **fields):
    """Raise ``warnings.warn(message)`` AND record it as a typed,
    counted event.

    Drop-in replacement for the pipeline's bare ``warnings.warn``
    sites: the warning semantics (category, filterability,
    ``pytest.warns``) are unchanged, and the event lands in the JSONL
    log plus the ``events.<kind>`` registry counter so end-of-run
    reports can state exactly what went sideways and how often.
    ``stacklevel`` defaults to 3 so the warning points at the caller's
    caller — the same frame the old inline ``warnings.warn`` blamed.
    """
    get_event_log().emit(kind, message, **fields)
    warnings.warn(message, category, stacklevel=stacklevel)
