"""Candidate provenance: the lineage ledger + selection-funnel audit.

Every raw peak the pipeline decodes gets a stable content-derived
candidate id (:func:`candidate_uid` — a hash of the run id and the
candidate's trial coordinates), and every selection decision between
decode and the survey store appends one typed *mark* to a rotating,
torn-tolerant ``lineage.jsonl`` stream:

====================  =====================================================
kind                  meaning
====================  =====================================================
``decoded``           a DM row's merged peaks entered the funnel (``ids``)
``clipped``           peaks lost to a capacity clip (aggregate ``n``)
``dropped``           decode under-delivery sentinels dropped (``n``)
``merged``            duplicate spectrum bins merged pre-candidate (``n``)
``superseded``        a whole decode pass discarded in favour of an
                      escalated re-search (aggregate ``n``)
``absorbed``          a distiller folded the candidate into ``absorber``
                      under ``rule`` with tolerance ``margin`` (terminal)
``cut``               dropped at the output ``limit`` cut (terminal)
``emitted``           survived to the SearchResult (terminal)
``scored``            scorer verdict flags (annotation)
``fold_cut``          in the fold period window but beyond top-npdmp
``folded``            selected for folding (annotation)
``stored``            ingested into the survey store (annotation)
``quarantined``       canary candidate kept out of science reads
====================  =====================================================

**Conservation invariant** (the timeline-waterfall pattern applied to
candidates): every ``decoded`` id reaches *exactly one* of the three
terminal states — ``absorbed``, ``cut`` or ``emitted`` — so

    ``n(decoded) == n(absorbed) + n(cut) + n(emitted)``

holds exactly.  :func:`check_conservation` proves it mechanically and
is asserted in tests and ``make lineage-smoke``.  ``clipped`` /
``dropped`` / ``merged`` / ``superseded`` account for peaks that never
entered the id'd population (lost before or instead of decode) and are
aggregate counts by design.

The writer self-accounts its own cost (the ``timeline.overhead()``
pattern): :func:`overhead` reports marks/seconds/errors so the serve
ledger can export ``lineage_overhead_s`` and the smoke can gate it
below 1% of drain wall-clock.  Marking is best-effort and never raises
— provenance must not kill a multi-hour search.  The stream schema is
declared in :mod:`.streams` so lint rule PSL013 proves writer/reader
agreement.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time

from .metrics import REGISTRY as METRICS

LINEAGE_VERSION = 1

#: rotate the live ledger past this size to ``<path>.1`` (one retained
#: generation, the events.jsonl / telemetry-shard scheme)
DEFAULT_MAX_BYTES = 16 * 1024 * 1024

#: the three states a decoded candidate may terminate in — exactly one
#: each (see CONTRIBUTING.md "Adding a decision kind")
TERMINAL_KINDS = ("absorbed", "cut", "emitted")

#: aggregate pre-decode loss accounting (counts, never ids)
AGGREGATE_KINDS = ("clipped", "dropped", "merged", "superseded")

#: non-terminal per-candidate annotations
ANNOTATION_KINDS = ("scored", "fold_cut", "folded", "stored",
                    "quarantined")

# self-accounted writer cost, the timeline.overhead() pattern: the
# plane that measures the pipeline must measure itself
_OV_LOCK = threading.Lock()
_OVERHEAD = {"marks": 0, "seconds": 0.0, "errors": 0}


def overhead() -> dict:
    """Total marks recorded, seconds spent recording them, and write
    errors, process-wide — exported as ``lineage_overhead_s`` in serve
    ledger records and gated <1% of drain wall-clock in the smoke."""
    with _OV_LOCK:
        return dict(_OVERHEAD)


def candidate_uid(run: str, cand) -> str:
    """Stable content-derived candidate id.

    Hash of the run id plus the candidate's trial coordinates
    (dm trial index, accel, jerk, harmonic level, frequency) — the
    fields fixed at decode time and never mutated afterwards (folding
    touches only ``folded_snr`` / ``opt_period``), so the id computed
    at decode, at store-ingest and from a parsed store record is
    identical.  Tolerates pre-jerk candidates (parsed overview.xml,
    legacy checkpoints) the way the binary writer does: missing
    ``dm_idx``/``jerk`` hash as zero."""
    return uid_from_fields(run, getattr(cand, "dm_idx", 0), cand.acc,
                           getattr(cand, "jerk", 0.0), cand.nh,
                           cand.freq)


def uid_from_fields(run: str, dm_idx, acc, jerk, nh, freq) -> str:
    """:func:`candidate_uid` from bare fields (store-record backfill,
    mesh decode arrays).  ``repr(float(...))`` is the shortest exact
    float round-trip, so json-serialised fields reproduce the id."""
    key = "|".join((
        str(run), str(int(dm_idx)), repr(float(acc)),
        repr(float(jerk)), str(int(nh)), repr(float(freq)),
    ))
    return hashlib.sha1(key.encode()).hexdigest()[:16]


class LineageRecorder:
    """Append-only JSONL mark sink with ``.1`` rotation.

    The handle opens lazily and is line-buffered; an I/O failure
    disables persistence for the rest of the run (counted in
    ``lineage.mark_errors``, never an exception)."""

    def __init__(self, path: str, run: str = "", *,
                 max_bytes: int = DEFAULT_MAX_BYTES):
        self.path = str(path)
        self.run = str(run)
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._file = None
        self._io_failed = False

    def _maybe_rotate(self) -> None:
        # caller holds the lock; errors are swallowed (a stat race
        # must not kill the emitting run)
        if self.max_bytes <= 0:
            return
        try:
            if os.path.getsize(self.path) < self.max_bytes:
                return
        except OSError:
            return  # no file yet
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
            self._file = None
        try:
            os.replace(self.path, self.path + ".1")
        except OSError:
            pass

    def mark(self, kind: str, *, run: str | None = None,
             **fields) -> None:
        """Append one typed decision mark; best-effort, never raises."""
        t0 = time.perf_counter()
        try:
            rec = {
                "v": LINEAGE_VERSION,
                "ts": round(time.time(), 6),
                "run": self.run if run is None else str(run),
                "kind": str(kind),
            }
            for k, v in fields.items():
                if v is not None:
                    rec[k] = v
            line = json.dumps(rec) + "\n"
            with self._lock:
                if self._io_failed:
                    return
                self._maybe_rotate()
                if self._file is None:
                    d = os.path.dirname(self.path)
                    if d:
                        os.makedirs(d, exist_ok=True)
                    self._file = open(self.path, "a", buffering=1)
                self._file.write(line)
            METRICS.inc("lineage.marks")
        except (OSError, TypeError, ValueError):
            with self._lock:
                self._io_failed = True
            METRICS.inc("lineage.mark_errors")
            with _OV_LOCK:
                _OVERHEAD["errors"] += 1
        finally:
            with _OV_LOCK:
                _OVERHEAD["marks"] += 1
                _OVERHEAD["seconds"] += time.perf_counter() - t0

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                finally:
                    self._file = None


_global_lock = threading.Lock()
_RECORDER: LineageRecorder | None = None


def configure_lineage(path: str, *, run: str = "",
                      max_bytes: int = DEFAULT_MAX_BYTES
                      ) -> LineageRecorder | None:
    """Point the process-wide lineage ledger at ``path`` (empty path
    disables it — the ``--no-lineage`` escape hatch)."""
    global _RECORDER
    with _global_lock:
        if _RECORDER is not None:
            _RECORDER.close()
        _RECORDER = (LineageRecorder(path, run, max_bytes=max_bytes)
                     if path else None)
        return _RECORDER


def get_lineage() -> LineageRecorder | None:
    return _RECORDER


def enabled() -> bool:
    """Cheap guard for instrumentation call sites: id hashing and mark
    assembly are skipped entirely when no ledger is configured."""
    return _RECORDER is not None


def mark(kind: str, *, run: str | None = None, **fields) -> None:
    """Module-level convenience: mark on the configured recorder, or
    no-op when lineage is off."""
    rec = _RECORDER
    if rec is not None:
        rec.mark(kind, run=run, **fields)


# -------------------------------------------------------------------------
# readers: torn-tolerant parse, funnel accounting, conservation proof
# -------------------------------------------------------------------------

def read_lineage(path: str, run: str | None = None) -> list[dict]:
    """Parse marks from ``path`` (rotated ``.1`` generation first, so
    order is append order).  Torn/garbage lines — a crashed writer's
    partial tail — are skipped, not fatal.  ``run`` filters to one
    run's marks."""
    marks: list[dict] = []
    for p in (path + ".1", path):
        try:
            fh = open(p, encoding="utf-8")
        except OSError:
            continue
        with fh:
            for line in fh:
                try:
                    m = json.loads(line)
                except (json.JSONDecodeError, ValueError):
                    continue  # torn line: tolerate, keep reading
                if not isinstance(m, dict):
                    continue
                if m.get("v") != LINEAGE_VERSION:
                    continue
                if run is not None and m.get("run") != run:
                    continue
                marks.append(m)
    return marks


def _mark_ids(m: dict) -> list[str]:
    if m.get("id") is not None:
        return [m["id"]]
    ids = m.get("ids")
    return list(ids) if ids else []


def funnel(marks, runs=None) -> dict:
    """Exact per-stage selection-funnel counts over ``marks``.

    Terminal/``decoded`` kinds count candidate *ids*; aggregate kinds
    sum their ``n`` fields.  ``pass_frac`` / ``absorbed_frac`` are the
    distillation-behaviour signals the baselines and the
    ``distill_collapse`` health rule watch."""
    if runs is not None:
        runs = set(runs)
        marks = [m for m in marks if m.get("run") in runs]
    counts = {k: 0 for k in
              ("decoded",) + TERMINAL_KINDS + AGGREGATE_KINDS}
    for m in marks:
        kind = m.get("kind")
        if kind == "decoded" or kind in TERMINAL_KINDS:
            counts[kind] += len(_mark_ids(m)) or int(m.get("n") or 0)
        elif kind in AGGREGATE_KINDS:
            counts[kind] += int(m.get("n") or 0)
    dec = counts["decoded"]
    counts["pass_frac"] = (counts["emitted"] / dec) if dec else 0.0
    counts["absorbed_frac"] = (counts["absorbed"] / dec) if dec else 0.0
    return counts


def check_conservation(marks, runs=None) -> list[str]:
    """Prove the conservation invariant; returns problem strings
    (empty list == the invariant holds).

    Every decoded id must appear in exactly one terminal state, every
    terminal id must have been decoded, and the stage counts must sum
    to the decoded count *exactly*."""
    if runs is not None:
        runs = set(runs)
        marks = [m for m in marks if m.get("run") in runs]
    decoded: set[str] = set()
    terminal: dict[str, list[str]] = {}
    n_terminal = 0
    for m in marks:
        kind = m.get("kind")
        if kind == "decoded":
            decoded.update(_mark_ids(m))
        elif kind in TERMINAL_KINDS:
            n_terminal += 1
            for cid in _mark_ids(m):
                terminal.setdefault(cid, []).append(kind)
    problems = []
    for cid, kinds in terminal.items():
        if len(kinds) > 1:
            problems.append(
                f"{cid}: {len(kinds)} terminal states {kinds}")
        if cid not in decoded:
            problems.append(f"{cid}: terminal {kinds[0]} but never "
                            f"decoded")
    for cid in decoded - set(terminal):
        problems.append(f"{cid}: decoded but reached no terminal state")
    if len(decoded) != n_terminal and not problems:
        problems.append(
            f"count mismatch: {len(decoded)} decoded != "
            f"{n_terminal} terminal marks")
    return problems


def why_chain(marks, cid: str, max_depth: int = 8) -> dict:
    """Reconstruct candidate ``cid``'s full decision chain from marks.

    Returns ``{"id", "run", "decoded", "terminal", "annotations",
    "absorbed_into", "children"}`` where ``children`` recurses into the
    candidates this one absorbed (an absorbed candidate may itself
    have absorbed others in an earlier stage)."""
    terminal = None
    absorbed_into = None
    annotations = []
    decoded = False
    run = None
    children_marks = []
    for m in marks:
        ids = _mark_ids(m)
        kind = m.get("kind")
        if kind == "decoded" and cid in ids:
            decoded = True
            run = m.get("run")
        elif cid in ids:
            if kind in TERMINAL_KINDS:
                terminal = m
                if kind == "absorbed":
                    absorbed_into = m.get("absorber")
            elif kind in ANNOTATION_KINDS:
                annotations.append(m)
            if run is None:
                run = m.get("run")
        if kind == "absorbed" and m.get("absorber") == cid:
            children_marks.append(m)
    children = []
    if max_depth > 0:
        for m in children_marks:
            children.append(why_chain(marks, m["id"],
                                      max_depth=max_depth - 1))
    return {
        "id": cid,
        "run": run,
        "decoded": decoded,
        "terminal": terminal,
        "annotations": annotations,
        "absorbed_into": absorbed_into,
        "children": children,
    }
