"""Analytical per-stage cost model + roofline utilization.

The pipeline's geometry is fully determined by the ``SearchPlan``
(nsamps, nchans, DM count, accel trials per DM, fft size, harmonic
stages, fold bins/top-N), so every stage's FLOPs and bytes are
computable in closed form — no profiling required.  This module is the
SINGLE source of truth for those figures (lint rule PSL007 rejects
hand-written FLOP/byte constants anywhere else): the span tree and the
metrics registry join cost x measured device time into achieved
FLOP/s, achieved B/s, arithmetic intensity and a roofline-style
``utilization`` fraction against a per-device peak table, emitted as
the ``perf`` section of ``run_report.json`` (see
:func:`perf_section`), surfaced by the CLI ``--verbose`` table and by
``bench.py``'s output/ledger columns.

Methodology (Williams, Waterman & Patterson, "Roofline: an insightful
visual performance model for multicore architectures", CACM 2009): for
a stage with F flops and B bytes of HBM traffic on a device with peak
compute P_f and peak stream bandwidth P_b,

    attainable FLOP/s = min(P_f, (F/B) * P_b)
    utilization       = (F / device_seconds) / attainable   (clamped to 1)

The closed forms below are *model* costs with documented coefficients
(e.g. a real FFT is counted as ``2.5 n log2 n`` flops); they are
cross-checked against XLA's own ``cost_analysis()`` to a documented
factor (:func:`crosscheck_registered_programs`,
``tests/test_perf.py``), so a formula drifting away from the traced
program fails a tier-1 test rather than silently mis-reporting.

Five stages are modelled — the same five programs the jaxpr lint
checker traces (``analysis/jaxpr_check.py:registered_programs``):

=============  ===========================================================
dedisperse     direct delay-sweep over (ndm, nchans, out_nsamps)
spectrum       the per-DM whiten chain (rfft, running median, deredden,
               interbin, stats, irfft) PLUS the per-accel-trial spectrum
               formation (resample, rfft, interbin, normalise) — the
               same ``form_interpolated`` code path both phases share
harmonics      stretched-and-summed spectra, levels 1..nharms
peaks          thresholded top-k extraction per (trial, harmonic level)
fold           re-whiten + resample + one-hot fold + PDMP optimise per
               folded candidate (npdmp upper bound)
=============  ===========================================================
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass

# --------------------------------------------------------------------------
# per-device peak table
# --------------------------------------------------------------------------

#: Per-chip peaks: dense f32 FLOP/s and HBM stream bandwidth (B/s).
#: TPU figures are the published per-chip numbers with the f32 peak
#: taken as half the bf16 MXU peak (this pipeline is f32 end to end);
#: the CPU fallback is a deliberately generous modern-server figure so
#: CPU utilization reads as a small fraction, never a fake 100 %.
PEAK_TABLE: dict[str, dict] = {
    "TPU v2":      {"flops_per_s": 23.0e12,  "bytes_per_s": 700.0e9},
    "TPU v3":      {"flops_per_s": 61.5e12,  "bytes_per_s": 900.0e9},
    "TPU v4":      {"flops_per_s": 137.5e12, "bytes_per_s": 1228.0e9},
    "TPU v5 lite": {"flops_per_s": 98.5e12,  "bytes_per_s": 819.0e9},
    "TPU v5p":     {"flops_per_s": 229.5e12, "bytes_per_s": 2765.0e9},
    "TPU v6 lite": {"flops_per_s": 459.0e12, "bytes_per_s": 1640.0e9},
    "cpu":         {"flops_per_s": 1.0e12,   "bytes_per_s": 100.0e9},
}

_DEFAULT_PEAK_KIND = "cpu"


def device_peak(kind: str | None = None, n_devices: int = 1) -> dict:
    """Peak figures for ``kind`` (a jax ``device_kind`` string; matched
    case-insensitively by table-key substring), scaled by the number of
    participating devices.  Unknown kinds fall back to the CPU entry
    with ``matched=False`` so consumers can flag the guess."""
    if kind is None:
        try:
            import jax

            kind = str(jax.devices()[0].device_kind)
        except Exception:
            kind = _DEFAULT_PEAK_KIND
    norm = str(kind).lower()
    entry, matched = None, False
    for key, val in PEAK_TABLE.items():
        if key.lower() in norm or norm in key.lower():
            entry, matched = val, True
            break
    if entry is None:
        entry = PEAK_TABLE[_DEFAULT_PEAK_KIND]
    n = max(int(n_devices), 1)
    return {
        "kind": str(kind),
        "matched": matched,
        "n_devices": n,
        "flops_per_s": entry["flops_per_s"] * n,
        "bytes_per_s": entry["bytes_per_s"] * n,
    }


# --------------------------------------------------------------------------
# stage cost primitive
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class StageCost:
    """Closed-form work estimate for one stage (or one program call)."""

    flops: float
    bytes_read: float
    bytes_written: float

    @property
    def bytes_total(self) -> float:
        return self.bytes_read + self.bytes_written

    @property
    def intensity(self) -> float:
        """Arithmetic intensity (flops per byte of traffic)."""
        return self.flops / max(self.bytes_total, 1.0)

    def dominant(self, peak: dict) -> str:
        """Which roof binds on ``peak``: ``"compute"`` or ``"memory"``."""
        t_f = self.flops / peak["flops_per_s"]
        t_b = self.bytes_total / peak["bytes_per_s"]
        return "compute" if t_f >= t_b else "memory"

    def scaled(self, k: float) -> "StageCost":
        return StageCost(self.flops * k, self.bytes_read * k,
                         self.bytes_written * k)

    def __add__(self, other: "StageCost") -> "StageCost":
        return StageCost(self.flops + other.flops,
                         self.bytes_read + other.bytes_read,
                         self.bytes_written + other.bytes_written)


ZERO_COST = StageCost(0.0, 0.0, 0.0)

#: model coefficient: one real FFT of length n costs 2.5 n log2 n flops
#: (the canonical 5 n log2 n for a complex transform, halved for r2c/c2r)
_FFT_REAL_COEFF = 2.5

#: f32 element size; the pipeline is f32 (c64 = two f32 lanes) end to end
_F32 = 4


def fft_real_flops(n: int) -> float:
    """Model flops of ONE real transform (rfft or irfft) of length n."""
    return _FFT_REAL_COEFF * n * math.log2(max(n, 2))


# -- per-call unit costs ----------------------------------------------------

def dedisperse_cost(n_dm: int, nchans: int, out_nsamps: int,
                    in_itemsize: int = 4,
                    out_itemsize: int = _F32) -> StageCost:
    """Direct delay sweep: one add per (DM row, channel, output sample).
    Each row re-reads the band at shifted offsets; the input traffic is
    counted at the stored sample width (u8 for packed filterbanks) and
    the output at the trial-lattice width (ISSUE 13: u8/bf16 lattices
    shrink the written trial rows the search stage streams back in)."""
    elems = float(n_dm) * nchans * out_nsamps
    return StageCost(
        flops=elems,
        bytes_read=elems * in_itemsize,
        bytes_written=float(n_dm) * out_nsamps * out_itemsize,
    )


def whiten_cost(n: int) -> StageCost:
    """One whiten_core call (rfft, power, scrunch-median cascade,
    deredden, interbin, stats, irfft) on an n-sample series.  The
    elementwise chain is ~30 flops per spectral bin (power 5, median
    cascade ~8, complex divide 8, interbin 9)."""
    nb = n // 2 + 1
    return StageCost(
        flops=2 * fft_real_flops(n) + 30.0 * nb,
        # tim in + fseries/pspec/median passes (c64 + 3 f32 vectors)
        bytes_read=n * _F32 + nb * (8 + 3 * _F32),
        bytes_written=n * _F32 + nb * (8 + 3 * _F32),
    )


def accel_spectrum_cost(n: int, trial_itemsize: int = _F32) -> StageCost:
    """One acceleration trial's spectrum formation: shift-select
    resample (1 flop/sample), rfft, interbin (~9 flops/bin), normalise
    (2 flops/bin).  The trial time series is read once at the lattice
    width (f32/bf16/u8) plus one f32 pass for the resample gather."""
    nb = n // 2 + 1
    return StageCost(
        flops=n + fft_real_flops(n) + 11.0 * nb,
        bytes_read=n * trial_itemsize + n * _F32 + nb * 8,
        bytes_written=n * _F32 + nb * (8 + _F32),
    )


def harmonics_cost(nbins: int, nharms: int) -> StageCost:
    """One harmonic_sums call: level k adds 2^(k-1) stretched terms to
    the previous level, so total adds are (2^nharms - 1) per bin; the
    traffic is the micro-benchmark's (2*nh+1) passes — nh+1 reads
    (previous level + stretched source) and nh writes."""
    return StageCost(
        flops=float((1 << nharms) - 1) * nbins,
        bytes_read=float(nharms + 1) * nbins * _F32,
        bytes_written=float(nharms) * nbins * _F32,
    )


#: modelled two-stage row width (ops/peaks.py narrow default) and the
#: compaction kernel's scatter lane chunk (ops/peaks_pallas.py)
_TWO_STAGE_MODEL_WIDTH = 128
_COMPACTION_SCATTER_CHUNK = 512


def peaks_cost(nbins: int, capacity: int,
               method: str = "sort") -> StageCost:
    """One extract_top_peaks call over one spectrum level, per
    extraction lowering (ops/peaks.py):

    * ``sort`` — a threshold compare per bin plus ~log2(capacity)
      compares per bin for the top-k selection network (what
      approx_max_k's recall_target=1.0 sort costs);
    * ``two_stage`` — mask + row-max reduce per bin, a log2(cap)
      selection over the nbins/C row maxima, then the small top_k over
      the cap*C gathered lanes;
    * ``pallas`` — the threshold-compaction kernel: one compare + one
      prefix-count add per bin streamed once from HBM, plus the
      survivor scatter's one-hot select (capacity x lane-chunk) —
      O(survivors), the whole point of the lowering.  Its roofline is
      the memory roof: intensity ~2 flops/byte.
    """
    cap = max(int(capacity), 2)
    if method == "two_stage":
        rows = max(float(nbins) / _TWO_STAGE_MODEL_WIDTH, 1.0)
        flops = (2.0 * nbins + (rows + cap * _TWO_STAGE_MODEL_WIDTH)
                 * math.log2(cap))
    elif method == "pallas":
        flops = 2.0 * nbins + float(cap) * _COMPACTION_SCATTER_CHUNK
    else:
        flops = nbins * (1.0 + math.log2(cap))
    return StageCost(
        flops=flops,
        bytes_read=float(nbins) * _F32,
        bytes_written=float(cap) * 2 * _F32,  # idx + snr slots
    )


def fold_program_cost(n: int, nbins: int = 64, nints: int = 16) -> StageCost:
    """One fold_time_series_core + optimise_device call (the registered
    ``fold`` program): ~2 flops/sample for the one-hot fold matmul,
    then the PDMP matched-filter search (`ops/fold.py:110-151`) — FFT
    the subints along phase, apply ``nshifts = nbins`` per-subint phase
    rotations, multiply by ``nbins - 1`` boxcar template transforms and
    inverse-transform every (template, shift) combination."""
    nshifts = nbins
    ntempl = max(nbins - 1, 1)
    comb = float(ntempl) * nshifts
    opt = (float(nshifts) * nints * nbins * 8.0   # phase rotations (c64)
           + comb * nbins * 8.0                   # template multiply-add
           + comb * 2.0 * fft_real_flops(nbins))  # per-combination ifft
    return StageCost(
        flops=2.0 * n + opt,
        bytes_read=n * _F32 + comb * nbins * 8,
        bytes_written=float(nints) * nbins * _F32 + comb * nbins * 8,
    )


def fold_candidate_cost(n: int, nbins: int = 64,
                        nints: int = 16) -> StageCost:
    """One folded candidate end to end: re-whiten (2 real FFTs + the
    median chain), resample, fold + optimise."""
    return whiten_cost(n) + StageCost(n, n * _F32, n * _F32) \
        + fold_program_cost(n, nbins, nints)


# --------------------------------------------------------------------------
# pipeline geometry -> per-stage totals
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class PipelineGeometry:
    """Everything the cost model needs, all derivable from the plan."""

    n_dm: int
    nchans: int
    out_nsamps: int
    in_itemsize: int
    size: int             # fft length
    nharmonics: int
    peak_capacity: int
    n_trials_total: int   # sum over DMs of that DM's accel-trial count
    npdmp: int
    fold_nsamps: int
    fold_nbins: int
    fold_nints: int
    #: resolved peak-extraction lowering of the deepest harmonic level
    #: (the largest searched prefix dominates the stage cost); selects
    #: the peaks_cost formula so the roofline table reflects the
    #: actual lowering, not always the sort
    peaks_method: str = "sort"
    #: leading observation axis of a batched dispatch (ISSUE 9): the
    #: fused program unrolls B beams of identical per-beam work, so
    #: every stage's flops/bytes scale linearly in B and roofline
    #: utilization stays meaningful for the batched program
    batch: int = 1
    #: jerk trials per (DM, accel) slot (ISSUE 13): 1 for accel-only
    #: searches; already folded into ``n_trials_total``, kept here so
    #: reports can show the axis explicitly
    njerk: int = 1
    #: resolved trial-lattice element size in bytes (f32=4, bf16=2,
    #: u8=1) — the width the dedisperse stage writes trial rows at and
    #: the spectrum stage streams them back in at
    trial_itemsize: int = _F32

    @classmethod
    def from_search(cls, search, acc_lists=None,
                    batch: int = 1) -> "PipelineGeometry":
        """Build from a ``PulsarSearch``-like driver.  ``acc_lists``
        (per-DM accel arrays — COMBINED accel x jerk lists when the
        mesh driver holds a jerk grid) skips regenerating the trial
        grid when the caller already holds it."""
        from ..search.plan import (
            FOLD_NBINS,
            FOLD_NINTS,
            prev_power_of_two,
            trial_grid_geometry,
        )
        from ..search.tuning import LATTICE_ITEMSIZE

        cfg = search.config
        jerk_plan = getattr(search, "jerk_plan", None)
        njerk = int(jerk_plan.njerk) if jerk_plan is not None else 1
        if acc_lists is not None:
            # mesh drivers pass combined (accel, jerk) lists — the sum
            # already counts the full trial product
            n_trials = int(sum(len(a) for a in acc_lists))
        else:
            n_trials = trial_grid_geometry(
                search.dm_list, search.acc_plan,
                jerk_plan=jerk_plan).n_trials_total
        lattice = str(getattr(search, "lattice", "f32"))
        peaks_method = "sort"
        try:
            # the deepest level searches the largest prefix and
            # dominates the modelled stage cost
            peaks_method = search.peaks_methods_for(
                int(cfg.peak_capacity))[-1]
        except Exception:
            pass
        return cls(
            peaks_method=str(peaks_method),
            batch=int(batch),
            njerk=njerk,
            trial_itemsize=int(LATTICE_ITEMSIZE.get(lattice, _F32)),
            n_dm=int(len(search.dm_list)),
            nchans=int(search.fil.nchans),
            out_nsamps=int(search.out_nsamps),
            in_itemsize=1 if search.fil.header.nbits <= 8 else 4,
            size=int(search.size),
            nharmonics=int(cfg.nharmonics),
            peak_capacity=int(cfg.peak_capacity),
            n_trials_total=n_trials,
            npdmp=int(cfg.npdmp),
            fold_nsamps=prev_power_of_two(int(search.out_nsamps)),
            fold_nbins=FOLD_NBINS,
            fold_nints=FOLD_NINTS,
        )

    def to_json(self) -> dict:
        out = {k: int(getattr(self, k)) for k in (
            "n_dm", "nchans", "out_nsamps", "in_itemsize", "size",
            "nharmonics", "peak_capacity", "n_trials_total", "npdmp",
            "fold_nsamps", "fold_nbins", "fold_nints", "batch",
            "njerk", "trial_itemsize")}
        out["peaks_method"] = str(self.peaks_method)
        return out


#: stage order = pipeline order = the jaxpr checker's program registry
STAGES = ("dedisperse", "spectrum", "harmonics", "peaks", "fold")


def pipeline_costs(geom: PipelineGeometry) -> dict[str, StageCost]:
    """Per-stage totals for one full search at ``geom`` — times
    ``geom.batch`` when the dispatch stacks B observations (each beam
    repeats the identical per-beam work, so totals are linear in B)."""
    nb = geom.size // 2 + 1
    nlevels = geom.nharmonics + 1
    spectrum = (whiten_cost(geom.size).scaled(geom.n_dm)
                + accel_spectrum_cost(
                    geom.size, geom.trial_itemsize).scaled(
                    geom.n_trials_total))
    peaks = peaks_cost(nb, geom.peak_capacity,
                       geom.peaks_method).scaled(
        nlevels * geom.n_trials_total)
    stages = {
        "dedisperse": dedisperse_cost(
            geom.n_dm, geom.nchans, geom.out_nsamps, geom.in_itemsize,
            out_itemsize=geom.trial_itemsize),
        "spectrum": spectrum,
        "harmonics": harmonics_cost(nb, geom.nharmonics).scaled(
            geom.n_trials_total),
        "peaks": peaks,
        "fold": fold_candidate_cost(
            geom.fold_nsamps, geom.fold_nbins, geom.fold_nints
        ).scaled(geom.npdmp),
    }
    if geom.batch > 1:
        stages = {k: v.scaled(geom.batch) for k, v in stages.items()}
    return stages


# --------------------------------------------------------------------------
# per-run cost holder (the drivers record, the report reads)
# --------------------------------------------------------------------------

_lock = threading.Lock()
_RUN_COSTS: dict | None = None


def record_run_costs(search, acc_lists=None, batch: int = 1) -> dict:
    """Compute and stash this run's stage costs (called once per run by
    each driver).  Also caches per-unit scalars on the search object so
    span call sites can attach ``gflops`` attributes cheaply.  Returns
    ``{"geometry": PipelineGeometry, "stages": {name: StageCost}}``.
    ``batch``: observation count of a batched dispatch (totals scale
    linearly; the per-trial/per-row scalars stay per-beam)."""
    global _RUN_COSTS
    geom = PipelineGeometry.from_search(search, acc_lists, batch=batch)
    stages = pipeline_costs(geom)
    costs = {"geometry": geom, "stages": stages}
    # per-accel-trial search work (spectrum formation + harmonic sums +
    # peak extraction) and per-DM-row work (whiten + dedisp row), in
    # Gflops — the scalars Accel-Search / Chunked-Search spans attach
    nb = geom.size // 2 + 1
    per_trial = (accel_spectrum_cost(geom.size, geom.trial_itemsize)
                 + harmonics_cost(nb, geom.nharmonics)
                 + peaks_cost(nb, geom.peak_capacity,
                              geom.peaks_method).scaled(
                     geom.nharmonics + 1))
    per_row = (whiten_cost(geom.size)
               + dedisperse_cost(1, geom.nchans, geom.out_nsamps,
                                 geom.in_itemsize))
    search._stage_costs = costs
    search._per_trial_gflops = per_trial.flops / 1e9
    search._per_dmrow_gflops = per_row.flops / 1e9
    with _lock:
        _RUN_COSTS = costs
    return costs


def get_run_costs() -> dict | None:
    with _lock:
        return _RUN_COSTS


def reset_run_costs() -> None:
    global _RUN_COSTS
    with _lock:
        _RUN_COSTS = None


# --------------------------------------------------------------------------
# cost x measured time -> the run report's perf section
# --------------------------------------------------------------------------

#: registry stage-timer names whose device seconds make up the search
#: pool (the fused/chunked programs have no internal stage boundaries)
_SEARCH_POOL_TIMERS = ("accel_search", "fused_search", "chunked_search")

#: stages with their own dedicated stage timer
_MEASURED_TIMERS = {"dedisperse": "dedispersion", "fold": "folding"}

#: stages that share the pooled search time when not separately
#: measured, apportioned by modelled roofline time
_POOLED_STAGES = ("spectrum", "harmonics", "peaks")


def _timer_seconds(timers: dict, name: str) -> tuple[float, str] | None:
    """(seconds, basis) for one stage timer: measured device seconds
    preferred, host wall-clock as the documented upper-bound fallback.
    None when the timer is absent or zero."""
    rec = timers.get(name)
    if not rec:
        return None
    dev = float(rec.get("device_s", 0.0))
    if dev > 0.0:
        return dev, "device"
    host = float(rec.get("host_s", 0.0))
    if host > 0.0:
        return host, "host"
    return None


def _roofline_time(cost: StageCost, peak: dict) -> float:
    """Modelled stage seconds on ``peak``: max of the compute and
    memory roofs (the roofline lower bound)."""
    return max(cost.flops / peak["flops_per_s"],
               cost.bytes_total / peak["bytes_per_s"])


def perf_section(run_costs: dict, timers: dict, device: dict,
                 gauges: dict | None = None) -> dict:
    """Join stage costs with measured stage timers into the
    ``run_report.json`` ``perf`` section.

    Stages with a dedicated timer (``dedispersion``, ``folding``) use
    it directly (``attribution: "measured"``); the stages fused into
    one search dispatch share the pooled search device time in
    proportion to their modelled roofline seconds (``attribution:
    "modeled-share"`` — by construction they then report the pool's
    common utilization).  A stage with no seconds available keeps its
    cost figures and simply omits the achieved/utilization keys — a
    consumer never sees nulls.
    """
    geom: PipelineGeometry = run_costs["geometry"]
    stages: dict[str, StageCost] = run_costs["stages"]
    gauges = gauges or {}
    kind = None
    for d in device.get("devices", []):
        kind = d.get("kind")
        break
    n_devices = int(gauges.get("search.n_devices", 1) or 1)
    peak = device_peak(kind, n_devices)

    # measured stages
    seconds: dict[str, tuple[float, str, str]] = {}
    pooled = list(_POOLED_STAGES)
    for stage, timer in _MEASURED_TIMERS.items():
        got = _timer_seconds(timers, timer)
        if got is not None:
            seconds[stage] = (got[0], got[1], "measured")
        elif stage == "dedisperse":
            pooled.insert(0, stage)  # fused into the search dispatch
    # pooled stages share the search timers
    pool_s, pool_basis = 0.0, "device"
    for name in _SEARCH_POOL_TIMERS:
        got = _timer_seconds(timers, name)
        if got is not None:
            pool_s += got[0]
            if got[1] == "host":
                pool_basis = "host"
    if pool_s > 0.0:
        t_model = {s: _roofline_time(stages[s], peak) for s in pooled}
        total = sum(t_model.values())
        if total > 0.0:
            for s in pooled:
                seconds[s] = (pool_s * t_model[s] / total, pool_basis,
                              "modeled-share")

    out_stages: dict[str, dict] = {}
    for name in STAGES:
        cost = stages[name]
        row: dict = {
            "flops": round(cost.flops),
            "bytes_read": round(cost.bytes_read),
            "bytes_written": round(cost.bytes_written),
            "dominant": cost.dominant(peak),
            "intensity_flops_per_byte": round(cost.intensity, 4),
        }
        got = seconds.get(name)
        if got is not None and got[0] > 0.0 and cost.flops > 0.0:
            secs, basis, attribution = got
            achieved_f = cost.flops / secs
            achieved_b = cost.bytes_total / secs
            attainable = min(peak["flops_per_s"],
                             cost.intensity * peak["bytes_per_s"])
            row.update(
                device_s=round(secs, 6),
                basis=basis,
                attribution=attribution,
                achieved_flops_per_s=round(achieved_f, 1),
                achieved_bytes_per_s=round(achieved_b, 1),
                # clamped: >1 would mean the peak-table entry
                # underestimates this device, not faster-than-roofline
                utilization=round(min(1.0, achieved_f / attainable), 6),
            )
        out_stages[name] = row
    total = StageCost(
        sum(c.flops for c in stages.values()),
        sum(c.bytes_read for c in stages.values()),
        sum(c.bytes_written for c in stages.values()),
    )
    return {
        "peak": {
            "kind": peak["kind"],
            "matched": peak["matched"],
            "n_devices": peak["n_devices"],
            "flops_per_s": peak["flops_per_s"],
            "bytes_per_s": peak["bytes_per_s"],
        },
        "geometry": geom.to_json(),
        "stages": out_stages,
        "total": {
            "flops": round(total.flops),
            "bytes": round(total.bytes_total),
            "intensity_flops_per_byte": round(total.intensity, 4),
        },
    }


def utilization_summary(perf: dict) -> dict[str, float]:
    """{stage: utilization} for the stages that have one (bench.py's
    ledger column)."""
    out = {}
    for name, row in (perf or {}).get("stages", {}).items():
        if "utilization" in row:
            out[name] = row["utilization"]
    return out


# --------------------------------------------------------------------------
# XLA cross-check
# --------------------------------------------------------------------------

#: documented agreement factor between the closed forms and XLA's own
#: cost_analysis(): the model counts algorithmic flops (an FFT is
#: 2.5 n log2 n) while XLA counts lowered HLO ops, so exact agreement
#: is impossible — but a formula drifting beyond this factor from the
#: traced program indicates the model no longer describes the code
CROSSCHECK_FACTOR = 32.0


def xla_cost_analysis(fn, args) -> dict | None:
    """``jax.jit(fn).lower(*args).compile().cost_analysis()`` distilled
    to ``{"flops", "bytes"}`` — or None when the backend/jax version
    does not provide it."""
    try:
        import jax

        compiled = jax.jit(fn).lower(*args).compile()
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = float(ca.get("flops", 0.0) or 0.0)
    nbytes = float(ca.get("bytes accessed", 0.0) or 0.0)
    return {"flops": flops, "bytes": nbytes}


def _crosscheck_shapes() -> dict[str, StageCost]:
    """Model costs at the SAME small shapes the jaxpr checker traces
    (``analysis/jaxpr_check.py:registered_programs``) — keep the two
    in sync; ``tests/test_perf.py`` asserts the name sets match."""
    return {
        # data (16 chans x 2048), delays (4 DMs, 16 chans), out 1024
        "dedisperse": dedisperse_cost(4, 16, 1024, in_itemsize=4),
        "spectrum": whiten_cost(2048),
        "harmonics": harmonics_cost(1025, 4),
        # capacity 32 over bins [1, 1000)
        "peaks": peaks_cost(1025, 32),
        "fold": fold_program_cost(16384, 64, 16),
    }


def crosscheck_registered_programs() -> list[dict]:
    """Compare the closed-form model against XLA's cost_analysis for
    each registered pipeline program at its lint-checker shape.

    Returns one row per program: ``{program, model_flops, xla_flops,
    ratio, ok}``.  ``xla_flops`` is None (and ``ok`` True) when the
    backend provides no analysis or reports zero flops (FFTs lower to
    custom calls XLA does not count) — the comparison is only
    meaningful where XLA actually counted work.
    """
    from ..analysis.jaxpr_check import registered_programs

    model = _crosscheck_shapes()
    rows: list[dict] = []
    for spec in registered_programs():
        est = model[spec.name]
        row = {"program": spec.name, "model_flops": est.flops,
               "xla_flops": None, "ratio": None, "ok": True}
        try:
            fn, args = spec.build()
            xla = xla_cost_analysis(fn, args)
        except Exception:
            xla = None
        if xla is not None and xla["flops"] > 0.0:
            ratio = est.flops / xla["flops"]
            row.update(
                xla_flops=xla["flops"], ratio=ratio,
                ok=(1.0 / CROSSCHECK_FACTOR <= ratio
                    <= CROSSCHECK_FACTOR),
            )
        rows.append(row)
    return rows
