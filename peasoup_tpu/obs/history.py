"""Bench history ledger: one JSONL record per benchmark run.

The committed ``BENCH_r0*.json`` artifacts were write-only — nothing
read or compared them, so a perf regression had to be spotted by a
human diffing JSON.  Every benchmark entry point now appends one
structured record to ``benchmarks/history.jsonl`` through the ONE
writer here (``bench.py``, ``benchmarks/micro.py`` and
``benchmarks/production.py`` all route through
:func:`make_history_record` + :func:`append_history`, so the ledger
has a single schema), and ``python -m peasoup_tpu.tools.perf_report``
loads it for trend tables and the noise-aware regression gate.

Record schema (``v`` = 1; consumers tolerate additions)::

    v               int     record schema version
    ts              str     ISO-8601 UTC timestamp
    kind            str     "bench" | "micro" | "production" | "serve"
    git             {sha, dirty}
    device          {kind, backend, count}
    mesh_shape      [int]   device mesh (absent for single-device)
    metrics         {name: number}   headline figures (e2e_s, ...)
    timers          {name: seconds}  driver wall-clock timers
    stage_device_s  {stage: seconds} per-stage measured device time
    utilization     {stage: fraction}  roofline utilization (costmodel)
    compile_counts  {name: int}      jit compile statistics
    parity          str     "ok" or the failure summary
    config          {...}   benchmark configuration echo

``kind == "serve"`` records are appended by the survey worker's drain
loop (``serve/worker.py``) with metrics ``jobs_claimed``,
``jobs_succeeded``, ``jobs_failed``, ``elapsed_s`` and
``jobs_per_hour`` — the survey-throughput headline the perf tooling
trends alongside the per-run benchmark figures.  Workers running with
``--batch B > 1`` additionally record ``batch`` (the configured stack
width), ``batched_dispatches`` (device round trips that carried more
than one observation) and ``batch_fill`` (total observations carried
by those dispatches — ``batch_fill / batched_dispatches`` is the mean
bucket fill), so the ledger can answer "did batching actually engage"
next to the ``jobs_per_hour`` it is supposed to move.  Drains that
completed jobs also carry the latency side of throughput —
``sojourn_p50``/``sojourn_p95`` (submit -> done, from the per-job
lifecycle timelines of ``obs/timeline.py``) and
``queue_wait_p50``/``queue_wait_p95`` — plus ``timeline_marks`` /
``timeline_overhead_s`` (the cost of writing those timelines, gated
under 1% by ``make loadgen-smoke``).  In fleet mode
(``serve/fleet.py``) every host appends its own record with
``config.host`` set to its fleet label, so per-host throughput can be
trended — and summed — from the same ledger ``status --fleet``
aggregates live.

``kind == "loadgen"`` records are appended once per saturation sweep
by ``tools/loadgen.py``: metrics ``rates_swept``, ``jobs_total`` /
``jobs_done`` / ``jobs_failed``, ``knee_rate_per_s`` and
``knee_throughput_per_s`` (the saturation knee the
``loadgen_saturation`` health rule compares live arrival rates
against), ``max_achieved_per_s`` and ``timeline_overhead_frac``, plus
a top-level ``rates`` list of slim per-rate rows (offered/achieved
rate, p50/p95/p99 sojourn, duty cycle, quarantined count) that
``tools/perf_report.py`` renders as the rate x percentile table.

``kind == "sensitivity"`` records are appended once per sensitivity
sweep by ``tools/sensitivity.py``: metrics ``cells`` / ``recovered``
/ ``recovery_fraction`` (the fraction of injected synthetic pulsars
the search recovered — the baseline the ``canary_recovery`` health
rule compares live canary traffic against), ``min_detectable_snr``
(lowest injected SNR with >= 50% recovery; omitted when the sweep
was inconclusive) and ``sweep_elapsed_s``, plus a top-level
``transfer`` list of per-injected-SNR rows (cells, recovered,
fraction, mean recovered SNR) that ``tools/perf_report.py`` renders
as the transfer-curve table.

``kind == "supervise"`` records are appended by the self-healing
supervisor (``serve/supervisor.py``) — exactly one per EXECUTED
action (dry-run and throttled plans never reach the ledger): metrics
``tick`` / ``workers_alive`` / ``queue_pending`` / ``queue_running``
at execution time, ``config.action`` naming the action, and a
top-level ``action`` object carrying ``name``, ``rule``,
``cooldown_s``, the action's ``outcome`` dict (what was reaped /
spawned / retired / retuned) and the triggering rule's
``finding_before`` / ``finding_after`` states — so "did the action
actually clear the finding" is answerable per record, and cooldown
enforcement is auditable from consecutive records' timestamps.

``kind == "chaos"`` records are appended once per chaos-harness run
(``tools/chaos.py``): metrics ``chaos_recovery_s`` (fault injection
to health exit-0, the figure ``bench.py --chaos`` prints and
``tools/perf_report.py`` trends/gates), ``faults_injected``,
``jobs_total`` / ``jobs_done`` / ``jobs_failed`` and
``admission_rejected``, with ``config`` echoing the seeded fault
plan.

Ledger I/O never raises into a benchmark run: append/load failures
warn and return best-effort results.
"""

from __future__ import annotations

import json
import os
import subprocess
import time

HISTORY_VERSION = 1

#: ledger filename, relative to the repo's ``benchmarks/`` directory
LEDGER_BASENAME = "history.jsonl"


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def default_ledger_path() -> str:
    return os.path.join(repo_root(), "benchmarks", LEDGER_BASENAME)


def git_describe(cwd: str | None = None) -> dict:
    """``{sha, dirty}`` of the working tree (best effort — a ledger
    without provenance is still a ledger)."""
    cwd = cwd or repo_root()
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"],
            capture_output=True, text=True, cwd=cwd, timeout=10,
        ).stdout.strip() or "unknown"
        dirty = bool(subprocess.run(
            ["git", "status", "--porcelain"],
            capture_output=True, text=True, cwd=cwd, timeout=10,
        ).stdout.strip())
    except Exception:
        return {"sha": "unknown", "dirty": False}
    return {"sha": sha, "dirty": dirty}


def _device_fields() -> dict:
    try:
        import jax

        devices = jax.devices()
        return {
            "kind": str(devices[0].device_kind),
            "backend": str(jax.default_backend()),
            "count": len(devices),
        }
    except Exception:
        return {"kind": "unknown", "backend": "unknown", "count": 0}


def stage_device_seconds(snapshot: dict) -> dict:
    """Per-stage measured device seconds out of a metrics-registry
    snapshot (``obs.metrics.MetricsRegistry.snapshot``)."""
    return {
        name: round(rec.get("device_s", 0.0), 6)
        for name, rec in snapshot.get("timers", {}).items()
        if rec.get("device_s", 0.0) > 0.0
    }


def make_history_record(kind: str, metrics: dict, *, timers=None,
                        stage_device_s=None, utilization=None,
                        compile_counts=None, parity=None, config=None,
                        mesh_shape=None, extra=None) -> dict:
    """Assemble one ledger record; only the provided sections are
    included (no nulls in the ledger)."""
    rec: dict = {
        "v": HISTORY_VERSION,
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "kind": str(kind),
        "git": git_describe(),
        "device": _device_fields(),
        "metrics": {
            k: v for k, v in (metrics or {}).items()
            if isinstance(v, (int, float)) and v is not None
        },
    }
    for key, val in (
        ("timers", timers), ("stage_device_s", stage_device_s),
        ("utilization", utilization), ("compile_counts", compile_counts),
        ("parity", parity), ("config", config),
        ("mesh_shape", mesh_shape),
    ):
        if val:
            rec[key] = val
    if extra:
        rec.update(extra)
    return rec


def append_history(record: dict, path: str | None = None) -> str | None:
    """Append one record to the ledger (creating it if absent).
    Returns the path written, or None on failure (warned, not
    raised — telemetry must never kill a benchmark run)."""
    path = path or default_ledger_path()
    try:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "a", encoding="utf-8") as f:
            f.write(json.dumps(record, sort_keys=True) + "\n")
    except OSError as exc:
        import warnings

        warnings.warn(f"could not append history record to "
                      f"{path!r}: {exc}")
        return None
    return path


def load_history(path: str | None = None,
                 kinds=None) -> list[dict]:
    """All ledger records in file order; corrupt lines are skipped (a
    torn tail from a killed run must not poison the whole history).
    ``kinds`` filters to the given record kinds."""
    path = path or default_ledger_path()
    out: list[dict] = []
    if not os.path.exists(path):
        return out
    wanted = set(kinds) if kinds else None
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(rec, dict):
                    continue
                if wanted is None or rec.get("kind") in wanted:
                    out.append(rec)
    except OSError:
        return out
    return out
