"""Span-tree-aligned structural diff of two runs (ISSUE 16).

``benchmarks/trace_summary_r*.md`` used to be written by a human
reading two profiler captures side by side.  The figures it quotes —
per-op device-time deltas, which ops appeared/vanished, compile-count
movement, the wall-clock line — are all mechanical joins over data
the run reports already carry, so this module computes them:

* :func:`diff_reports` — align two ``run_report.json`` documents on
  span name / stage name and produce per-entry host+device deltas,
  jit compile-count deltas, roofline-utilization deltas and
  candidate-set deltas;
* :func:`diff_bench_records` — the same join over two history-ledger
  bench records (``stage_device_s`` / ``compile_counts`` /
  ``utilization`` / headline metrics);
* :func:`render_markdown` — the trace-summary-shaped markdown that
  ``bench.py`` now writes as ``trace_summary_rN.md`` automatically
  and ``peasoup obs diff`` prints.

Everything is a pure function of the two input documents — no clock,
no globals — so a diff of two checked-in fixtures is reproducible
byte for byte.
"""

from __future__ import annotations

import json

from .warehouse import geometry_fingerprint

DIFF_VERSION = 1


def load_report(path: str) -> dict:
    """Load one ``run_report.json`` (raises on a missing/corrupt file:
    the CLI turns this into a clean exit 2)."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path!r}: not a run report (not an object)")
    return doc


def _entry_diff(a: dict, b: dict, field: str) -> dict:
    va = float(a.get(field, 0.0) or 0.0)
    vb = float(b.get(field, 0.0) or 0.0)
    out = {
        "a": round(va, 6), "b": round(vb, 6),
        "delta": round(vb - va, 6),
        "count_a": int(a.get("count", 0)),
        "count_b": int(b.get("count", 0)),
    }
    if va > 0:
        out["ratio"] = round(vb / va, 4)
    elif vb > 0:
        out["new"] = True
    return out


def _table_diff(ta: dict, tb: dict, field: str) -> dict:
    """Align two name->record tables on name; deltas for ``field``,
    ordered by descending |delta| with a name tiebreak — fully
    deterministic, so the rendered summary is byte-reproducible
    regardless of hash seeds."""
    ta, tb = ta or {}, tb or {}
    names = sorted(set(ta) | set(tb))
    rows = {name: _entry_diff(ta.get(name, {}), tb.get(name, {}),
                              field)
            for name in names}
    return dict(sorted(rows.items(),
                       key=lambda kv: (-abs(kv[1]["delta"]), kv[0])))


def _scalar_diff(va, vb, ndigits: int = 6) -> dict:
    va = float(va or 0.0)
    vb = float(vb or 0.0)
    out = {"a": round(va, ndigits), "b": round(vb, ndigits),
           "delta": round(vb - va, ndigits)}
    if va > 0:
        out["ratio"] = round(vb / va, 4)
    return out


def diff_reports(a: dict, b: dict, *, label_a: str = "a",
                 label_b: str = "b") -> dict:
    """Structural diff of two run reports (see module docstring)."""
    perf_a = a.get("perf", {}) or {}
    perf_b = b.get("perf", {}) or {}
    util_a = {s: r.get("utilization") for s, r in
              (perf_a.get("stages", {}) or {}).items()
              if r.get("utilization") is not None}
    util_b = {s: r.get("utilization") for s, r in
              (perf_b.get("stages", {}) or {}).items()
              if r.get("utilization") is not None}
    fp_a = geometry_fingerprint(perf_a.get("geometry"))
    fp_b = geometry_fingerprint(perf_b.get("geometry"))
    kinds = [d.get("kind", "") for d in
             (a.get("device", {}) or {}).get("devices", [])]
    kinds_b = [d.get("kind", "") for d in
               (b.get("device", {}) or {}).get("devices", [])]
    return {
        "v": DIFF_VERSION,
        "labels": [str(label_a), str(label_b)],
        "e2e_s": _scalar_diff((a.get("timers", {}) or {}).get("total"),
                              (b.get("timers", {}) or {}).get("total")),
        "spans": _table_diff(a.get("spans"), b.get("spans"),
                             "device_s"),
        "stages": _table_diff(a.get("stage_timers"),
                              b.get("stage_timers"), "device_s"),
        "stages_host": _table_diff(a.get("stage_timers"),
                                   b.get("stage_timers"), "host_s"),
        "compiles": _scalar_diff(
            (a.get("jit", {}) or {}).get("backend_compiles"),
            (b.get("jit", {}) or {}).get("backend_compiles")),
        "compile_s": _scalar_diff(
            (a.get("jit", {}) or {}).get("compile_s"),
            (b.get("jit", {}) or {}).get("compile_s")),
        "utilization": {
            s: _scalar_diff(util_a.get(s), util_b.get(s))
            for s in sorted(set(util_a) | set(util_b))},
        "candidates": _scalar_diff(
            (a.get("candidates", {}) or {}).get("count"),
            (b.get("candidates", {}) or {}).get("count")),
        "geometry": {"a": fp_a, "b": fp_b, "same": fp_a == fp_b},
        "device_kind": {"a": kinds[0] if kinds else "",
                        "b": kinds_b[0] if kinds_b else ""},
    }


def diff_bench_records(a: dict, b: dict, *, label_a: str = "a",
                       label_b: str = "b") -> dict:
    """The same structural diff over two history-ledger records
    (bench rounds): ``stage_device_s``, ``compile_counts``,
    ``utilization`` and the headline ``e2e_s`` metric."""
    sa = {s: {"device_s": v}
          for s, v in (a.get("stage_device_s", {}) or {}).items()}
    sb = {s: {"device_s": v}
          for s, v in (b.get("stage_device_s", {}) or {}).items()}
    fp_a = geometry_fingerprint(
        (a.get("config", {}) or {}).get("geometry",
                                        a.get("config", {})))
    fp_b = geometry_fingerprint(
        (b.get("config", {}) or {}).get("geometry",
                                        b.get("config", {})))
    util_a = a.get("utilization", {}) or {}
    util_b = b.get("utilization", {}) or {}
    return {
        "v": DIFF_VERSION,
        "labels": [str(label_a), str(label_b)],
        "e2e_s": _scalar_diff(
            (a.get("metrics", {}) or {}).get("e2e_s"),
            (b.get("metrics", {}) or {}).get("e2e_s")),
        "spans": {},
        "stages": _table_diff(sa, sb, "device_s"),
        "stages_host": {},
        "compiles": _scalar_diff(
            (a.get("compile_counts", {}) or {}).get("timed"),
            (b.get("compile_counts", {}) or {}).get("timed")),
        "compile_s": _scalar_diff(0.0, 0.0),
        "utilization": {
            s: _scalar_diff(util_a.get(s), util_b.get(s))
            for s in sorted(set(util_a) | set(util_b))},
        "candidates": _scalar_diff(0.0, 0.0),
        "geometry": {"a": fp_a, "b": fp_b, "same": fp_a == fp_b},
        "device_kind": {
            "a": (a.get("device", {}) or {}).get("kind", ""),
            "b": (b.get("device", {}) or {}).get("kind", "")},
    }


# --------------------------------------------------------------------------
# markdown rendering (the generated trace_summary_rN.md)
# --------------------------------------------------------------------------

def _ms(seconds: float) -> str:
    return f"{float(seconds) * 1e3:.1f}"


def _fmt_ratio(row: dict) -> str:
    if row.get("new"):
        return "new"
    if "ratio" in row:
        return f"{row['ratio']:.2f}x"
    return "-"


def _movers_table(rows: dict, heading: str, out: list,
                  limit: int = 12) -> None:
    rows = {name: row for name, row in rows.items()
            if row["a"] or row["b"]}
    if not rows:
        return
    out.append(heading)
    out.append("")
    out.append("| ms (a) | ms (b) | delta ms | ratio | count a->b "
               "| name |")
    out.append("|---|---|---|---|---|---|")
    for name, row in list(rows.items())[:limit]:
        out.append(
            f"| {_ms(row['a'])} | {_ms(row['b'])} "
            f"| {float(row['delta']) * 1e3:+.1f} | {_fmt_ratio(row)} "
            f"| {row['count_a']}->{row['count_b']} | {name} |")
    out.append("")


def render_markdown(diff: dict, *, title: str | None = None) -> str:
    """Render one structural diff as a trace-summary-shaped markdown
    document (deterministic: pure function of the diff)."""
    la, lb = diff.get("labels", ["a", "b"])
    out: list[str] = []
    out.append(title or f"# Run-to-run diff: {la} -> {lb}")
    out.append("")
    out.append(f"Generated by `peasoup obs diff` "
               f"(schema v{diff.get('v', DIFF_VERSION)}).")
    out.append("")
    e2e = diff.get("e2e_s", {})
    if e2e.get("a") or e2e.get("b"):
        ratio = f", {e2e['ratio']:.2f}x" if "ratio" in e2e else ""
        out.append(f"Wall-clock e2e: {e2e['a']:.3f} s -> "
                   f"{e2e['b']:.3f} s ({e2e['delta']:+.3f} s{ratio})")
    comp = diff.get("compiles", {})
    out.append(f"Backend compiles: {comp.get('a', 0):.0f} -> "
               f"{comp.get('b', 0):.0f} "
               f"({comp.get('delta', 0):+.0f})")
    geom = diff.get("geometry", {})
    if geom:
        note = ("same geometry"
                if geom.get("same") else "GEOMETRY CHANGED")
        out.append(f"Geometry: {geom.get('a') or '-'} -> "
                   f"{geom.get('b') or '-'} ({note})")
    dev = diff.get("device_kind", {})
    if dev.get("a") or dev.get("b"):
        out.append(f"Device: {dev.get('a') or '-'} -> "
                   f"{dev.get('b') or '-'}")
    out.append("")
    _movers_table(diff.get("spans", {}),
                  "Top device-time movers (span table):", out)
    _movers_table(diff.get("stages", {}),
                  "Per-stage device time:", out)
    util = {s: row for s, row in diff.get("utilization", {}).items()
            if row.get("a") or row.get("b")}
    if util:
        out.append("Roofline utilization:")
        out.append("")
        out.append("| stage | util (a) | util (b) | delta |")
        out.append("|---|---|---|---|")
        for stage, row in util.items():
            out.append(f"| {stage} | {row['a']:.3f} | {row['b']:.3f} "
                       f"| {row['delta']:+.3f} |")
        out.append("")
    cand = diff.get("candidates", {})
    if cand.get("a") or cand.get("b"):
        out.append(f"Candidates: {cand['a']:.0f} -> {cand['b']:.0f} "
                   f"({cand['delta']:+.0f})")
        out.append("")
    return "\n".join(out).rstrip() + "\n"


def write_trace_summary(path: str, diff: dict, *,
                        title: str | None = None) -> str:
    """Write the rendered markdown atomically; returns the path."""
    from ..utils.atomicio import atomic_write_text

    atomic_write_text(path, render_markdown(diff, title=title))
    return path
