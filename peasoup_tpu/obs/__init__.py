"""Run-telemetry subsystem (no reference equivalent).

The reference instruments its pipeline with NVTX ranges and a
wall-clock ``<execution_times>`` XML block (`include/utils/nvtx.hpp`,
`src/pipeline_multi.cu`); everything else — buffer overflows, re-runs,
recompiles — is invisible.  At production scale those signals must be
counted, logged and reported per run, so this package provides:

* :mod:`.metrics` — a thread-safe process-wide registry of counters,
  gauges and stage timers that split host wall-clock from device time
  (``block_until_ready`` deltas), plus jit-compile tracking;
* :mod:`.events` — a structured JSONL event log whose
  :func:`~peasoup_tpu.obs.events.warn_event` both raises the usual
  Python warning and records a typed, counted event (every
  ``warnings.warn`` site in ``search/`` and ``parallel/`` routes
  through it — enforced by a repo lint test);
* :mod:`.report` — an end-of-run machine-readable ``run_report.json``
  (timers, counters, events, device info, HBM figures, candidate
  statistics) written next to ``overview.xml``;
* :mod:`.trace` — hierarchical span tracing with per-chunk/per-trial
  attribution, HBM watermarks, Chrome trace-event (Perfetto) export
  and multihost merge; :func:`~peasoup_tpu.obs.trace.span` is the ONE
  API pipeline stages time themselves with (lint rule PSL006);
* :mod:`.costmodel` — the analytical per-stage FLOP/byte cost model
  and roofline utilization join (the SINGLE source of truth for
  FLOP/byte constants, lint rule PSL007), feeding the report's
  ``perf`` section;
* :mod:`.history` — the bench history ledger
  (``benchmarks/history.jsonl``) every benchmark entry point appends
  to, read by ``python -m peasoup_tpu.tools.perf_report``;
* :mod:`.warehouse` — the flight recorder (ISSUE 16): every stream
  above flattened into ONE schema-versioned, append-only row store
  keyed by (run, stage, geometry fingerprint, device kind, host);
* :mod:`.baseline` — rolling robust (median/MAD) baselines per
  warehouse key, emitting typed ``kind:"anomaly"`` records;
* :mod:`.lineage` — the candidate provenance ledger (ISSUE 19):
  stable content-derived candidate ids, one typed mark per selection
  decision (``lineage.jsonl``), exact funnel accounting with the
  conservation invariant ``decoded == absorbed + cut + emitted``,
  and the decision-chain reconstruction behind the ``why`` verb;
* :mod:`.diff` — span-tree-aligned structural diff of two runs,
  rendered as the generated ``trace_summary_rN.md``;
* :mod:`.catalog` — the metrics catalog every literal
  ``METRICS.inc``/``gauge`` name must appear in (lint rule PSL009).
"""

from .metrics import REGISTRY, MetricsRegistry, install_compile_hook
from .events import EventLog, configure_event_log, get_event_log, warn_event
from .report import build_run_report, format_stage_table, write_run_report
from .trace import (
    Tracer,
    get_tracer,
    span,
    span_table,
    write_merged_trace,
)
from .costmodel import (
    PipelineGeometry,
    StageCost,
    device_peak,
    perf_section,
    pipeline_costs,
    record_run_costs,
)
from .history import append_history, load_history, make_history_record
from .warehouse import Warehouse, geometry_fingerprint, host_rollup
from .baseline import (
    baseline_band,
    baseline_table,
    funnel_anomalies,
    history_anomalies,
    write_anomalies,
)
from .lineage import (
    candidate_uid,
    check_conservation,
    configure_lineage,
    funnel,
    read_lineage,
    why_chain,
)
from .diff import diff_bench_records, diff_reports, render_markdown
from .catalog import CATALOG, DYNAMIC_PREFIXES, is_cataloged

__all__ = [
    "REGISTRY", "MetricsRegistry", "install_compile_hook",
    "EventLog", "configure_event_log", "get_event_log", "warn_event",
    "build_run_report", "format_stage_table", "write_run_report",
    "Tracer", "get_tracer", "span", "span_table", "write_merged_trace",
    "PipelineGeometry", "StageCost", "device_peak", "perf_section",
    "pipeline_costs", "record_run_costs",
    "append_history", "load_history", "make_history_record",
    "Warehouse", "geometry_fingerprint", "host_rollup",
    "baseline_band", "baseline_table", "funnel_anomalies",
    "history_anomalies", "write_anomalies",
    "candidate_uid", "check_conservation", "configure_lineage",
    "funnel", "read_lineage", "why_chain",
    "diff_bench_records", "diff_reports", "render_markdown",
    "CATALOG", "DYNAMIC_PREFIXES", "is_cataloged",
]
