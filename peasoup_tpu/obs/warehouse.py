"""The flight recorder's system of record (ISSUE 16).

PRs 1/11/12/14 left five disjoint artifact streams on disk —
``run_report.json`` (per run), the span table inside it, per-job
``timeline.jsonl`` marks, per-host ``fleet/ts-<host>.jsonl``
telemetry shards and the ``benchmarks/history.jsonl`` ledger — and
every cross-run question ("why is r07 slower than r06?") required a
human to open all five.  This module ingests each stream into ONE
schema-versioned, append-only store of flat *rows*, keyed by

    (run id, stage, geometry fingerprint, device kind, host)

so :mod:`.baseline` can maintain robust per-key baselines and
:mod:`.diff` can align any two runs structurally (the Dapper
trace-aggregation shape: raw spans below, queryable rollups above).

Design rules, inherited from the telemetry plane:

* **Append-only segments with bounded disk.**  Rows land in
  ``segment.jsonl``; once it exceeds ``max_segment_bytes`` it is
  *sealed* by renaming to ``segment.jsonl.1`` (dropping any previous
  sealed generation) — byte-for-byte the ``ts-<host>.jsonl``
  ``.1``-generation scheme from :mod:`.telemetry`, so a long-lived
  fleet's warehouse occupies at most two segment files.
* **Torn lines are skipped, never fatal** (a killed writer must not
  poison later readers); lines with ``v`` *newer* than
  :data:`WAREHOUSE_VERSION` are skipped and counted, and the reader
  emits one counted ``warehouse_schema_skew`` warn_event per read —
  old readers degrade gracefully against new writers.
* **Merged ordering is by row timestamp**, not file order, so rows
  ingested from hosts with skewed clocks interleave deterministically
  (stable sort on ``(ts, host, source, metric)``).
* **The index is derived state.**  ``index.json`` summarises per-run
  row counts / time spans for ``obs query``; it is rebuilt from the
  segments whenever it is missing or stale, never trusted blindly.

I/O failures degrade to a warning + latched no-op, like the sampler:
the warehouse must never kill the run it is recording.
"""

from __future__ import annotations

import hashlib
import json
import os
import time

from ..utils.atomicio import atomic_write_json
from .streams import stream_version

#: schema version stamped on every row; readers skip (and count)
#: rows from the future (sourced from the stream catalog so the two
#: can never drift — PSL013 checks literal version constants against
#: the catalog, and a catalog-sourced constant is exempt by design)
WAREHOUSE_VERSION = stream_version("warehouse")

#: seal (rotate) the live segment past this size — same default scale
#: as the telemetry shards
DEFAULT_MAX_SEGMENT_BYTES = 4 * 1024 * 1024

SEGMENT_BASENAME = "segment.jsonl"
INDEX_BASENAME = "index.json"

#: unicode ramp shared by ``status --watch`` and ``perf_report``
SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def sparkline(values, width: int = 24) -> str:
    """Render ``values`` as a fixed-height unicode sparkline."""
    vals = [float(v) for v in values][-int(width):]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return SPARK_BLOCKS[0] * len(vals)
    scale = (len(SPARK_BLOCKS) - 1) / (hi - lo)
    return "".join(SPARK_BLOCKS[int((v - lo) * scale)] for v in vals)


def geometry_fingerprint(geometry) -> str:
    """Stable short fingerprint of a geometry (or any config) dict —
    the key component that lets baselines refuse to compare runs of
    different problem shapes."""
    if not geometry:
        return ""
    blob = json.dumps(geometry, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode()).hexdigest()[:12]


def _iso_to_epoch(ts) -> float | None:
    """Parse the ledger/report ISO-8601 UTC stamp to epoch seconds."""
    if isinstance(ts, (int, float)):
        return float(ts)
    if not ts:
        return None
    try:
        import datetime

        s = str(ts).replace("Z", "+00:00")
        dt = datetime.datetime.fromisoformat(s)
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=datetime.timezone.utc)
        return dt.timestamp()
    except ValueError:
        return None


def make_row(*, ts: float, run: str, source: str, metric: str,
             value: float, stage: str = "", geometry: str = "",
             device_kind: str = "", host: str = "",
             data: dict | None = None) -> dict:
    """One warehouse row.  ``(run, stage, geometry, device_kind,
    host)`` is the key; ``metric``/``value`` the measurement."""
    row = {
        "v": WAREHOUSE_VERSION,
        "ts": round(float(ts), 6),
        "run": str(run),
        "source": str(source),
        "stage": str(stage),
        "geometry": str(geometry),
        "device_kind": str(device_kind),
        "host": str(host),
        "metric": str(metric),
        "value": float(value),
    }
    if data:
        row["data"] = data
    return row


def row_key(row: dict) -> tuple:
    """The warehouse key of a row (run id excluded: baselines compare
    the same (stage, geometry, device kind, host) *across* runs)."""
    return (row.get("stage", ""), row.get("geometry", ""),
            row.get("device_kind", ""), row.get("host", ""))


class Warehouse:
    """One warehouse directory: live + sealed segment, index."""

    def __init__(self, root: str, *,
                 max_segment_bytes: int = DEFAULT_MAX_SEGMENT_BYTES,
                 clock=time.time):
        self.root = str(root)
        self.max_segment_bytes = int(max_segment_bytes)
        self._clock = clock
        self._io_failed = False
        #: per-read skip statistics ({"torn": n, "skew": n}), for
        #: tests and the CLI's footer line
        self.last_skipped = {"torn": 0, "skew": 0}

    # -- paths -------------------------------------------------------------

    @property
    def segment_path(self) -> str:
        return os.path.join(self.root, SEGMENT_BASENAME)

    @property
    def index_path(self) -> str:
        return os.path.join(self.root, INDEX_BASENAME)

    def _generations(self) -> list[str]:
        """Sealed-then-live segment paths, oldest first."""
        return [self.segment_path + ".1", self.segment_path]

    # -- writing -----------------------------------------------------------

    def append_rows(self, rows) -> int:
        """Append rows to the live segment (sealing it first if it has
        outgrown the budget); returns the number written.  Never
        raises on I/O failure — warns once and latches off, like the
        telemetry sampler."""
        rows = [r for r in rows if r.get("metric")]
        if not rows or self._io_failed:
            return 0
        try:
            os.makedirs(self.root, exist_ok=True)
            self._maybe_seal()
            with open(self.segment_path, "a") as f:
                for row in rows:
                    row.setdefault("v", WAREHOUSE_VERSION)
                    f.write(json.dumps(row, sort_keys=True) + "\n")
            self._update_index(rows)
        except OSError as exc:
            self._io_failed = True
            from .events import warn_event

            warn_event("warehouse_io_failed",
                       f"warehouse disabled: could not write "
                       f"{self.segment_path!r}: {exc}",
                       path=self.segment_path)
            return 0
        return len(rows)

    def _maybe_seal(self) -> None:
        """Seal the live segment once it exceeds the byte budget —
        the ``ts-<host>.jsonl`` ``.1`` scheme: at most one sealed
        generation is retained, so disk stays bounded at roughly
        ``2 * max_segment_bytes``."""
        try:
            if os.path.getsize(self.segment_path) \
                    >= self.max_segment_bytes:
                os.replace(self.segment_path, self.segment_path + ".1")
        except OSError:
            pass  # no live segment yet

    # -- index -------------------------------------------------------------

    def _update_index(self, new_rows) -> None:
        index = self._load_index()
        runs = index.setdefault("runs", {})
        for row in new_rows:
            ent = runs.setdefault(row.get("run", ""), {
                "rows": 0, "ts_min": row["ts"], "ts_max": row["ts"],
                "sources": []})
            ent["rows"] += 1
            ent["ts_min"] = min(ent["ts_min"], row["ts"])
            ent["ts_max"] = max(ent["ts_max"], row["ts"])
            if row.get("source") and row["source"] not in ent["sources"]:
                ent["sources"] = sorted(
                    set(ent["sources"]) | {row["source"]})
        index["rows_total"] = index.get("rows_total", 0) + len(new_rows)
        atomic_write_json(self.index_path, index, sort_keys=True)

    def _load_index(self) -> dict:
        try:
            with open(self.index_path) as f:
                doc = json.load(f)
            if isinstance(doc, dict):
                return doc
        except (OSError, ValueError):
            pass
        return {"v": WAREHOUSE_VERSION, "runs": {}, "rows_total": 0}

    def index(self) -> dict:
        """The per-run index (rebuilt from segments if missing)."""
        doc = self._load_index()
        if not doc.get("runs") and any(
                os.path.exists(p) for p in self._generations()):
            return self.reindex()
        return doc

    def reindex(self) -> dict:
        """Rebuild ``index.json`` from the segment files."""
        try:
            os.remove(self.index_path)
        except OSError:
            pass
        rows = self.rows()
        if rows:
            try:
                self._update_index(rows)
            except OSError:
                pass
        return self._load_index()

    # -- reading -----------------------------------------------------------

    def rows(self, *, run: str | None = None, stage: str | None = None,
             host: str | None = None, metric: str | None = None,
             source: str | None = None,
             since: float | None = None) -> list[dict]:
        """All matching rows from sealed + live segments, merged in
        timestamp order (cross-host clock skew tolerated: ordering is
        by the rows' own ``ts``, with a deterministic tiebreak).

        Torn/corrupt lines are skipped silently; rows stamped with a
        *newer* schema version are skipped and counted, and one
        ``warehouse_schema_skew`` warn_event carries the count."""
        out: list[dict] = []
        torn = skew = 0
        for path in self._generations():
            try:
                with open(path) as f:
                    raw = f.read()
            except OSError:
                continue
            for line in raw.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    torn += 1
                    continue
                if not isinstance(row, dict) or "ts" not in row:
                    torn += 1
                    continue
                if int(row.get("v", 0)) > WAREHOUSE_VERSION:
                    skew += 1
                    continue
                out.append(row)
        self.last_skipped = {"torn": torn, "skew": skew}
        if skew:
            from .events import warn_event

            warn_event("warehouse_schema_skew",
                       f"skipped {skew} warehouse row(s) newer than "
                       f"schema v{WAREHOUSE_VERSION} (reader too old)",
                       skipped=skew, reader_version=WAREHOUSE_VERSION)
        if run is not None:
            out = [r for r in out if r.get("run") == run]
        if stage is not None:
            out = [r for r in out if r.get("stage") == stage]
        if host is not None:
            out = [r for r in out if r.get("host") == host]
        if source is not None:
            out = [r for r in out if r.get("source") == source]
        if metric is not None:
            out = [r for r in out
                   if str(r.get("metric", "")).startswith(metric)]
        if since is not None:
            out = [r for r in out if r.get("ts", 0.0) >= since]
        out.sort(key=lambda r: (r.get("ts", 0.0), r.get("host", ""),
                                r.get("source", ""), r.get("metric", "")))
        return out

    def top(self, n: int = 10, **filters) -> list[dict]:
        """The ``n`` largest-valued rows matching ``filters``."""
        rows = self.rows(**filters)
        rows.sort(key=lambda r: -r.get("value", 0.0))
        return rows[:max(0, int(n))]

    def tail(self, n: int = 10, **filters) -> list[dict]:
        """The ``n`` most recent rows matching ``filters``."""
        rows = self.rows(**filters)
        return rows[-max(0, int(n)):]

    # -- ingest: run reports ----------------------------------------------

    def ingest_run_report(self, report: dict, *, run: str = "",
                          host: str = "") -> int:
        """Flatten one ``run_report.json`` (schema v2) into rows:
        stage timers, the span table, jit-compile figures, roofline
        utilization and the candidate summary."""
        rows = run_report_rows(report, run=run, host=host,
                               clock=self._clock)
        return self.append_rows(rows)

    # -- ingest: history ledger -------------------------------------------

    def ingest_history(self, records) -> int:
        """Flatten bench/serve/... ledger records into rows (one run
        id per record, from its ISO timestamp)."""
        rows: list[dict] = []
        for rec in records:
            rows.extend(history_rows(rec, clock=self._clock))
        return self.append_rows(rows)

    # -- ingest: telemetry shards -----------------------------------------

    def ingest_telemetry(self, ts_dir: str, *, hosts=None,
                         since: float | None = None,
                         run: str = "fleet") -> int:
        """Flatten per-host telemetry samples (counter deltas, timer
        device seconds, gauges) into rows."""
        from .telemetry import read_samples

        rows: list[dict] = []
        for sample in read_samples(ts_dir, hosts=hosts, since=since):
            rows.extend(telemetry_rows(sample, run=run))
        return self.append_rows(rows)

    # -- ingest: compile ledger -------------------------------------------

    def ingest_compiles(self, path: str, *, run: str = "") -> int:
        """Flatten a ``compiles.jsonl`` ledger (obs/compilation.py)
        into rows — per-compile durations keyed by (program, geometry
        fingerprint, device kind), recompile markers, cache
        engagements and profiler-capture artifact paths."""
        from .compilation import read_compiles

        rows: list[dict] = []
        for rec in read_compiles(path):
            rows.extend(compile_rows(rec, run=run, clock=self._clock))
        return self.append_rows(rows)

    # -- ingest: lineage ledger --------------------------------------------

    def ingest_lineage(self, path: str, *,
                       run: str | None = None) -> int:
        """Flatten a ``lineage.jsonl`` ledger (obs/lineage.py, ISSUE
        19) into rows: one ``lineage.<kind>`` count row per mark, plus
        per-run selection-funnel rates (``lineage.pass_frac``,
        ``lineage.absorbed_frac``, ``lineage.decoded``) computed by
        the ledger's own :func:`~peasoup_tpu.obs.lineage.funnel` — the
        series :mod:`.baseline` bands so a distillation behaviour
        shift surfaces as a ``kind:"anomaly"`` record."""
        from . import lineage

        marks = lineage.read_lineage(path, run=run)
        rows = lineage_rows(marks, clock=self._clock)
        for rid in sorted({r["run"] for r in rows if r["run"]}):
            fn = lineage.funnel(marks, runs=[rid])
            if not fn["decoded"]:
                continue
            common = dict(
                ts=max(r["ts"] for r in rows if r["run"] == rid),
                run=rid, source="lineage", stage="funnel")
            for name in ("pass_frac", "absorbed_frac", "decoded"):
                rows.append(make_row(metric=f"lineage.{name}",
                                     value=float(fn[name]), **common))
        return self.append_rows(rows)

    # -- ingest: timelines -------------------------------------------------

    def ingest_timeline(self, path_or_workdir: str, *,
                        run: str = "") -> int:
        """Flatten per-job timeline marks into rows (one per mark,
        stage = phase)."""
        from .timeline import read_timeline

        rows: list[dict] = []
        for mark in read_timeline(path_or_workdir):
            # marks carry "t_wall" (see obs/streams.py); this used to
            # read "ts"/"job" — keys no mark writer ever produces — so
            # timeline ingestion silently dropped every row (PSL013)
            ts = mark.get("t_wall")
            if ts is None:
                continue
            rows.append(make_row(
                ts=float(ts), run=run,
                source="timeline", stage=str(mark.get("phase", "")),
                host=str(mark.get("host", "")),
                metric="timeline.mark", value=1.0,
                data={k: v for k, v in mark.items()
                      if k in ("attempt",)}))
        return self.append_rows(rows)


# --------------------------------------------------------------------------
# stream flatteners (pure: dict in, rows out)
# --------------------------------------------------------------------------

def run_report_rows(report: dict, *, run: str = "", host: str = "",
                    clock=time.time) -> list[dict]:
    """Rows for one run report (see :class:`Warehouse`)."""
    ts = _iso_to_epoch(report.get("generated_utc"))
    if ts is None:
        ts = clock()
    run = run or str(report.get("generated_utc", "run"))
    device = report.get("device", {}) or {}
    kinds = [d.get("kind", "") for d in device.get("devices", [])]
    device_kind = kinds[0] if kinds else str(device.get("backend", ""))
    geom = geometry_fingerprint(
        (report.get("perf", {}) or {}).get("geometry"))
    common = dict(ts=ts, run=run, host=host, geometry=geom,
                  device_kind=device_kind)
    rows: list[dict] = []
    for name, t in (report.get("timers", {}) or {}).items():
        rows.append(make_row(source="report", metric=f"timer.{name}",
                             value=float(t), **common))
    for stage, rec in (report.get("stage_timers", {}) or {}).items():
        for field in ("host_s", "device_s", "count"):
            if field in rec:
                rows.append(make_row(
                    source="report", stage=stage,
                    metric=f"stage.{field}", value=float(rec[field]),
                    **common))
    for name, rec in (report.get("spans", {}) or {}).items():
        for field in ("device_s", "total_s", "self_s", "count"):
            if field in rec:
                rows.append(make_row(
                    source="span", stage=name,
                    metric=f"span.{field}", value=float(rec[field]),
                    **common))
    jit = report.get("jit", {}) or {}
    for field in ("backend_compiles", "compile_s"):
        if field in jit:
            rows.append(make_row(source="report",
                                 metric=f"jit.{field}",
                                 value=float(jit[field]), **common))
    perf = report.get("perf", {}) or {}
    for stage, rec in (perf.get("stages", {}) or {}).items():
        for field in ("utilization", "intensity_flops_per_byte",
                      "device_s"):
            if rec.get(field) is not None:
                rows.append(make_row(
                    source="roofline", stage=stage,
                    metric=f"roofline.{field}",
                    value=float(rec[field]), **common))
    cands = report.get("candidates", {}) or {}
    if "count" in cands:
        rows.append(make_row(source="report", metric="candidates.count",
                             value=float(cands["count"]), **common))
    return rows


def history_rows(rec: dict, *, clock=time.time) -> list[dict]:
    """Rows for one history-ledger record."""
    ts = _iso_to_epoch(rec.get("ts"))
    if ts is None:
        ts = clock()
    kind = str(rec.get("kind", "record"))
    run = f"{kind}@{rec.get('ts', int(ts))}"
    device_kind = str((rec.get("device", {}) or {}).get("kind", ""))
    cfg = rec.get("config", {}) or {}
    geom = geometry_fingerprint(cfg.get("geometry", cfg))
    host = str(cfg.get("worker", ""))
    common = dict(ts=ts, run=run, host=host, geometry=geom,
                  device_kind=device_kind)
    rows: list[dict] = []
    for name, value in (rec.get("metrics", {}) or {}).items():
        if isinstance(value, (int, float)):
            rows.append(make_row(source="history",
                                 metric=f"metric.{name}",
                                 value=float(value), **common))
    for stage, dev_s in (rec.get("stage_device_s", {}) or {}).items():
        rows.append(make_row(source="history", stage=stage,
                             metric="stage.device_s",
                             value=float(dev_s), **common))
    for stage, util in (rec.get("utilization", {}) or {}).items():
        rows.append(make_row(source="history", stage=stage,
                             metric="roofline.utilization",
                             value=float(util), **common))
    for name, count in (rec.get("compile_counts", {}) or {}).items():
        rows.append(make_row(source="history",
                             metric=f"jit.compiles.{name}",
                             value=float(count), **common))
    return rows


def compile_rows(rec: dict, *, run: str = "",
                 clock=time.time) -> list[dict]:
    """Rows for one compile-ledger record.

    ``kind:"compile"`` yields a ``compile.duration_s`` row keyed by
    (stage=program, geometry fingerprint, device kind) plus a
    ``compile.recompile`` marker when the key had been seen before;
    ``kind:"cache"`` / ``kind:"profile"`` yield engagement/artifact
    rows (the profile row's ``data.path`` registers the capture
    artifact in the warehouse)."""
    ts = rec.get("ts")
    if ts is None:
        ts = clock()
    run = run or f"pid:{rec.get('pid', 0)}"
    host = str(rec.get("host", ""))
    kind = str(rec.get("kind", ""))
    rows: list[dict] = []
    if kind == "compile":
        common = dict(
            ts=float(ts), run=run, host=host,
            stage=str(rec.get("program") or ""),
            geometry=str(rec.get("geometry") or ""),
            device_kind=str(rec.get("device_kind") or ""))
        rows.append(make_row(
            source="compiles", metric="compile.duration_s",
            value=float(rec.get("duration_s") or 0.0),
            data={"span": str(rec.get("span") or "")}, **common))
        if rec.get("seen_before"):
            rows.append(make_row(
                source="compiles", metric="compile.recompile",
                value=1.0, **common))
    elif kind == "cache":
        rows.append(make_row(
            ts=float(ts), run=run, host=host, source="compiles",
            metric="compile.cache_enabled",
            value=1.0 if rec.get("enabled") else 0.0,
            data={"dir": str(rec.get("dir") or "")}))
    elif kind == "profile":
        rows.append(make_row(
            ts=float(ts), run=run, host=host, source="compiles",
            metric="profile.capture", value=1.0,
            data={"path": str(rec.get("path") or "")}))
    return rows


def lineage_rows(marks, *, clock=time.time) -> list[dict]:
    """Rows for lineage-ledger marks (obs/lineage.py, ISSUE 19) — a
    declared reader of the ``lineage`` stream (PSL013): one
    ``lineage.<kind>`` row per mark, valued at the number of
    candidates the mark covers (``n`` for aggregates, the id list's
    length, else 1 for single-candidate marks)."""
    rows: list[dict] = []
    for m in marks:
        ts = m.get("ts")
        if ts is None:
            ts = clock()
        n = m.get("n")
        if n is None:
            ids = m.get("ids")
            n = len(ids) if isinstance(ids, list) else 1
        rows.append(make_row(
            ts=float(ts), run=str(m.get("run", "") or ""),
            source="lineage", stage=str(m.get("stage", "") or ""),
            host=str(m.get("host", "") or ""),
            metric="lineage." + str(m.get("kind", "mark")),
            value=float(n)))
    return rows


def telemetry_rows(sample: dict, *, run: str = "fleet") -> list[dict]:
    """Rows for one telemetry sample (counter deltas, per-stage timer
    device seconds, gauges)."""
    ts = float(sample.get("ts", 0.0))
    host = str(sample.get("host", ""))
    common = dict(ts=ts, run=run, host=host)
    rows: list[dict] = []
    for name, delta in (sample.get("counters", {}) or {}).items():
        rows.append(make_row(source="telemetry",
                             metric=f"counter.{name}",
                             value=float(delta), **common))
    for stage, rec in (sample.get("timers", {}) or {}).items():
        for field in ("device_s", "host_s"):
            if rec.get(field):
                rows.append(make_row(
                    source="telemetry", stage=stage,
                    metric=f"stage.{field}", value=float(rec[field]),
                    **common))
    for name, value in (sample.get("gauges", {}) or {}).items():
        if isinstance(value, (int, float)):
            rows.append(make_row(source="telemetry",
                                 metric=f"gauge.{name}",
                                 value=float(value), **common))
    return rows


# --------------------------------------------------------------------------
# fleet rollup (``status --watch``'s per-host columns)
# --------------------------------------------------------------------------

def host_rollup(ts_dir: str, *, window_s: float = 300.0,
                now: float | None = None) -> dict:
    """Per-host live rollup straight off the telemetry shards:

    * ``duty`` — device seconds per wall second over the window (the
      per-host duty cycle);
    * ``util`` — HBM high-water over budget, when both gauges exist
      (memory-side utilization; ``None`` on backends without stats);
    * ``jobs_per_hour`` — the gauge's recent series, sparkline-ready;
    * ``last_ts`` — the newest sample's timestamp (staleness).
    """
    from .telemetry import read_samples

    now = time.time() if now is None else float(now)
    rollup: dict[str, dict] = {}
    for sample in read_samples(ts_dir, since=now - window_s):
        host = str(sample.get("host", ""))
        ent = rollup.setdefault(host, {
            "duty": 0.0, "util": None, "jobs_per_hour": [],
            "last_ts": 0.0, "_device_s": 0.0, "_t0": None})
        ts = float(sample.get("ts", 0.0))
        ent["last_ts"] = max(ent["last_ts"], ts)
        if ent["_t0"] is None:
            ent["_t0"] = ts
        for rec in (sample.get("timers", {}) or {}).values():
            ent["_device_s"] += float(rec.get("device_s", 0.0) or 0.0)
        gauges = sample.get("gauges", {}) or {}
        jph = gauges.get("scheduler.jobs_per_hour")
        if jph is not None:
            ent["jobs_per_hour"].append(float(jph))
        high = gauges.get("hbm.high_water_bytes")
        budget = gauges.get("hbm.budget_bytes")
        if high and budget:
            ent["util"] = float(high) / float(budget)
    for ent in rollup.values():
        span = max(ent["last_ts"] - (ent["_t0"] or ent["last_ts"]),
                   1e-9)
        ent["duty"] = min(ent.pop("_device_s") / span, 1.0)
        ent.pop("_t0", None)
    return rollup
