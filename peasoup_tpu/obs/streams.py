"""The artifact-stream contract catalog (ISSUE 17, PSL013).

PR 16's :mod:`.catalog` declared every *metric name*; this module
does the same for the *artifact streams* — the schema-versioned
record shapes that cross process (and PR) boundaries on disk:
``events.jsonl``, telemetry shards, per-job timelines, the history
ledger, warehouse rows and ``run_report.json``.  Each entry declares
the stream's schema version, its required and optional record keys,
and the **binding sites**: which functions write records (their
emitted dict literals are statically checked by lint rule PSL013),
which functions read them (every ``rec["k"]`` / ``rec.get("k")`` on
the declared variable must name a declared key — a reader key no
writer can produce is dead code or a typo), and which module constant
mirrors the version (a drifted literal is a lint failure; constants
*sourced from this catalog*, like ``WAREHOUSE_VERSION``, are exempt
because they cannot drift).

Like :mod:`.catalog` this module is pure data — it imports nothing,
so the analysis package (and tests) can read it without dragging in
jax.  Streams whose records admit caller-chosen extension keys
(timeline ``**attrs``, history ``extra`` merges) list the known ones
as optional; merges via ``dict.update`` are by design outside the
static checker's reach, but every *literal* key is in contract.

Adding a stream, or a key to one: extend the entry here first, then
the writer — PSL013 fails the build when the code and the declaration
disagree, in either direction.  See CONTRIBUTING.md.
"""

from __future__ import annotations

#: stream name -> contract.  Binding sites are repo-relative posix
#: paths plus the function qualname (``Class.method`` or ``func``);
#: writer/reader tuples carry the record variable name checked inside
#: that function (``None`` = check dict literals only).
STREAMS: dict[str, dict] = {
    "events": {
        "version": 1,
        "version_key": "v",
        "version_const": ("peasoup_tpu/obs/events.py", "SCHEMA_VERSION"),
        "required": ("v", "ts", "kind", "message"),
        "optional": ("data",),
        "writers": (
            ("peasoup_tpu/obs/events.py", "EventLog.emit", "rec"),
            ("peasoup_tpu/obs/events.py", "EventLog._flood_summary", None),
        ),
        "readers": (),
        "doc": "typed warn/info event lines (events.jsonl)",
    },
    "telemetry": {
        "version": 1,
        "version_key": "v",
        "version_const": ("peasoup_tpu/obs/telemetry.py",
                          "TS_SCHEMA_VERSION"),
        "required": ("v", "ts", "host", "pid", "seq", "interval_s",
                     "counters", "timers", "gauges", "overhead_s"),
        # extras() merge keys are caller-chosen; the known ones:
        "optional": ("extras_error", "queue", "claimed", "jobs_done"),
        "writers": (
            ("peasoup_tpu/obs/telemetry.py",
             "TelemetrySampler.sample_now", "rec"),
        ),
        "readers": (
            ("peasoup_tpu/obs/telemetry.py", "read_samples", "r"),
            ("peasoup_tpu/obs/telemetry.py", "latest_by_host", "rec"),
            ("peasoup_tpu/obs/warehouse.py", "telemetry_rows", "sample"),
        ),
        "doc": "per-host fleet/ts-<host>.jsonl sampler shards",
    },
    "timeline": {
        "version": 1,
        "version_key": "v",
        "version_const": ("peasoup_tpu/obs/timeline.py",
                          "TIMELINE_VERSION"),
        "required": ("v", "phase", "t_wall", "t_mono", "host", "pid",
                     "attempt"),
        # **attrs keys stamped by the spool/worker/recorder call sites:
        "optional": ("priority", "tenant", "worker", "leader",
                     "resumes", "from_state", "dead_host", "span",
                     "device_s", "compile"),
        "writers": (
            ("peasoup_tpu/obs/timeline.py", "mark", "rec"),
        ),
        "readers": (
            ("peasoup_tpu/obs/timeline.py", "read_timeline", "rec"),
            ("peasoup_tpu/obs/warehouse.py",
             "Warehouse.ingest_timeline", "mark"),
        ),
        "doc": "per-job lifecycle marks (timeline.jsonl)",
    },
    "history": {
        "version": 1,
        "version_key": "v",
        "version_const": ("peasoup_tpu/obs/history.py",
                          "HISTORY_VERSION"),
        "required": ("v", "ts", "kind"),
        "optional": (
            # make_history_record sections
            "git", "device", "metrics", "timers", "stage_device_s",
            "utilization", "compile_counts", "parity", "config",
            "mesh_shape",
            # anomaly records (obs/baseline.py) ride the same ledger
            "key", "metric", "value", "median", "mad", "band",
            "z_score", "direction", "severity",
        ),
        "writers": (
            ("peasoup_tpu/obs/history.py", "make_history_record",
             "rec"),
            ("peasoup_tpu/obs/baseline.py", "make_anomaly", None),
        ),
        "readers": (
            ("peasoup_tpu/obs/warehouse.py", "history_rows", "rec"),
        ),
        "doc": "benchmarks/history.jsonl ledger (bench/serve/anomaly "
               "records)",
    },
    "compiles": {
        "version": 1,
        "version_key": "v",
        # COMPILES_VERSION is *sourced from* this entry (no literal
        # to drift), so no version_const binding
        "version_const": None,
        "required": ("v", "ts", "host", "pid", "kind"),
        # kind:"compile" carries the attribution keys; kind:"cache"
        # carries enabled/dir; kind:"profile" carries path
        "optional": ("program", "geometry", "device_kind",
                     "duration_s", "seen_before", "span", "enabled",
                     "dir", "path", "data"),
        "writers": (
            ("peasoup_tpu/obs/compilation.py", "CompileLedger.record",
             "rec"),
        ),
        "readers": (
            ("peasoup_tpu/obs/compilation.py", "read_compiles",
             "rec"),
            ("peasoup_tpu/obs/compilation.py", "summarize_compiles",
             "rec"),
            ("peasoup_tpu/obs/warehouse.py", "compile_rows", "rec"),
            ("peasoup_tpu/obs/baseline.py", "compile_anomalies",
             "rec"),
            ("peasoup_tpu/obs/cli.py", "cmd_compiles", "rec"),
        ),
        "doc": "geometry-keyed XLA compile ledger (compiles.jsonl)",
    },
    "warehouse": {
        "version": 1,
        "version_key": "v",
        # WAREHOUSE_VERSION is *sourced from* this entry (no literal
        # to drift), so no version_const binding
        "version_const": None,
        "required": ("v", "ts", "run", "source", "stage", "geometry",
                     "device_kind", "host", "metric", "value"),
        "optional": ("data",),
        "writers": (
            ("peasoup_tpu/obs/warehouse.py", "make_row", "row"),
        ),
        "readers": (
            ("peasoup_tpu/obs/warehouse.py", "Warehouse.rows", "row"),
            ("peasoup_tpu/obs/warehouse.py", "Warehouse.rows", "r"),
            ("peasoup_tpu/obs/warehouse.py", "Warehouse.top", "r"),
            ("peasoup_tpu/obs/warehouse.py", "Warehouse.tail", "r"),
            ("peasoup_tpu/obs/warehouse.py", "row_key", "row"),
        ),
        "doc": "flattened warehouse/segment.jsonl rows",
    },
    "lineage": {
        "version": 1,
        "version_key": "v",
        "version_const": ("peasoup_tpu/obs/lineage.py",
                          "LINEAGE_VERSION"),
        "required": ("v", "ts", "run", "kind"),
        # per-kind payload fields (mark(**fields) merge; the known
        # ones): id/ids for candidate marks, n for aggregates,
        # absorber/rule/margin for absorptions, trial coordinates and
        # rank for terminal marks, scorer flags for annotations
        "optional": ("id", "ids", "n", "stage", "rule", "absorber",
                     "margin", "dm_idx", "acc", "jerk", "nh", "freq",
                     "snr", "rank", "flags", "host"),
        "writers": (
            ("peasoup_tpu/obs/lineage.py", "LineageRecorder.mark",
             "rec"),
        ),
        "readers": (
            ("peasoup_tpu/obs/lineage.py", "read_lineage", "m"),
            ("peasoup_tpu/obs/lineage.py", "_mark_ids", "m"),
            ("peasoup_tpu/obs/lineage.py", "funnel", "m"),
            ("peasoup_tpu/obs/lineage.py", "check_conservation", "m"),
            ("peasoup_tpu/obs/lineage.py", "why_chain", "m"),
            ("peasoup_tpu/obs/warehouse.py", "lineage_rows", "m"),
            ("peasoup_tpu/serve/cli.py", "_render_why_mark", "m"),
        ),
        "doc": "per-candidate selection-decision marks "
               "(lineage.jsonl)",
    },
    "run_report": {
        "version": 2,
        "version_key": "schema_version",
        "version_const": ("peasoup_tpu/obs/report.py",
                          "REPORT_VERSION"),
        "required": ("schema_version", "version", "generated_utc",
                     "timers", "stage_timers", "counters", "gauges",
                     "spans", "events", "jit", "device"),
        # conditional sections + bench's `extra` merge keys
        "optional": ("perf", "memory", "candidates", "config",
                     "n_dm_trials", "n_accel_trials_dm0", "parity",
                     "vs_baseline"),
        "writers": (
            ("peasoup_tpu/obs/report.py", "build_run_report",
             "report"),
        ),
        "readers": (
            ("peasoup_tpu/obs/warehouse.py", "run_report_rows",
             "report"),
        ),
        "doc": "per-run run_report.json (schema v2)",
    },
}


def stream_version(name: str) -> int:
    """The declared schema version of ``name`` (the single source of
    truth — ``obs/warehouse.py`` imports its version from here)."""
    return int(STREAMS[name]["version"])


def stream_keys(name: str) -> frozenset[str]:
    """All keys a record of stream ``name`` may carry."""
    ent = STREAMS[name]
    return frozenset(ent["required"]) | frozenset(ent["optional"])


def writer_bindings() -> dict[tuple[str, str], tuple[str, str | None]]:
    """(relpath, qualname) -> (stream, record varname) for every
    declared writer site."""
    out: dict[tuple[str, str], tuple[str, str | None]] = {}
    for stream, ent in STREAMS.items():
        for relpath, qualname, varname in ent["writers"]:
            out[(relpath, qualname)] = (stream, varname)
    return out


def reader_bindings() -> dict[tuple[str, str], list[tuple[str, str]]]:
    """(relpath, qualname) -> [(stream, varname), ...] for every
    declared reader site."""
    out: dict[tuple[str, str], list[tuple[str, str]]] = {}
    for stream, ent in STREAMS.items():
        for relpath, qualname, varname in ent["readers"]:
            out.setdefault((relpath, qualname), []).append(
                (stream, varname))
    return out


def version_bindings() -> dict[tuple[str, str], tuple[str, int]]:
    """(relpath, constname) -> (stream, version) for every stream
    whose version is mirrored in a module constant."""
    out: dict[tuple[str, str], tuple[str, int]] = {}
    for stream, ent in STREAMS.items():
        const = ent.get("version_const")
        if const:
            out[tuple(const)] = (stream, int(ent["version"]))
    return out
