"""Hierarchical span tracing: always-on, structured, per-trial.

The reference instruments its pipeline with NVTX push/pop ranges
(`include/utils/nvtx.hpp:8-24`, `src/pipeline_multi.cu:144,207,318`)
that are invisible unless a profiler is attached; the PR-1 stage
timers aggregate per stage but cannot attribute time to an individual
chunk or DM trial.  This module is the layer both lacked:

* :func:`span` — a nestable context manager recording a
  :class:`SpanRecord` (id, parent id, wall-clock start/end, measured
  device time via ``handle.block`` / ``add_device_time``, structured
  attributes, jit-compile delta, HBM watermark) into the process-wide
  :class:`Tracer`.  It forwards the span name to
  ``jax.profiler.TraceAnnotation`` so a live ``--profile_dir`` capture
  still sees the same named ranges, and (when ``metric=`` is given)
  feeds the PR-1 stage-timer registry so ``run_report.json``'s
  ``stage_timers`` keep their host/device split.  ONE call site
  replaces the old ``trace_range(...)`` + ``METRICS.timer(...)`` pair
  (enforced outside ``obs/`` by lint rule PSL006).
* Chrome trace-event export (:func:`chrome_events`,
  :func:`write_merged_trace`) — balanced ``B``/``E`` phase pairs,
  monotonic timestamps per thread, span attributes in ``args`` — the
  file loads directly in Perfetto / ``chrome://tracing``.
* :func:`span_table` — per-name totals with **self** time (total
  minus direct children), merged into ``run_report.json``.
* Multihost aggregation — every process serialises its local spans
  (:func:`local_trace_payload`, pid-tagged with
  ``jax.process_index()``); ``parallel.multihost.gather_host_payloads``
  all-gathers the payloads and process 0 writes the merged trace.

HBM watermarks: :func:`hbm_watermark` polls ``device.memory_stats()``
on every local device at span close (``bytes_in_use`` /
``peak_bytes_in_use`` maxima).  Backends without memory stats (CPU)
return ``None`` on the first probe and sampling is disabled for the
rest of the process — a graceful no-op, never an error.  Supported
backends additionally maintain the run-level ``hbm.high_water_bytes``
gauge in the metrics registry.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field

from ..utils.atomicio import atomic_write_json
from .metrics import REGISTRY

#: hard cap on retained spans per process — a runaway per-element
#: instrumentation bug must degrade to dropped spans (counted in the
#: ``trace.spans_dropped`` metric), not unbounded host memory
MAX_SPANS = 100_000

_COMPILE_COUNTER = "jit.backend_compiles"


def hbm_watermark() -> dict | None:
    """Max ``bytes_in_use`` / ``peak_bytes_in_use`` over local devices,
    or None when the backend has no memory stats (CPU) — the caller
    treats None as "unsupported" and stops polling.  Delegates to
    :func:`.memprof.hbm_watermark`, the one ``memory_stats`` call site
    in the tree (ISSUE 18)."""
    from .memprof import hbm_watermark as _impl

    return _impl()


def _process_index() -> int:
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


class SpanHandle:
    """Yielded by :func:`span`.  The timed block calls :meth:`block`
    wherever it would ``block_until_ready`` (the wait is charged to the
    span — and the stage timer — as device time) and :meth:`set` to
    attach attributes discovered mid-span."""

    __slots__ = ("name", "span_id", "parent_id", "tid", "metric",
                 "attrs", "device_s", "t_start", "t_end", "_compiles0")

    def __init__(self, name, span_id, parent_id, tid, metric, attrs,
                 compiles0):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.tid = tid
        self.metric = metric
        self.attrs = attrs
        self.device_s = 0.0
        self.t_start = 0.0
        self.t_end = 0.0
        self._compiles0 = compiles0

    def set(self, **attrs) -> None:
        """Attach/overwrite span attributes."""
        self.attrs.update(attrs)

    def block(self, tree):
        """``jax.block_until_ready(tree)``, charging the wait to the
        span's device time.  Returns ``tree`` for call-through use."""
        import jax

        t0 = time.perf_counter()
        jax.block_until_ready(tree)
        self.device_s += time.perf_counter() - t0
        return tree

    def add_device_time(self, seconds: float) -> None:
        """Charge externally-measured device/link seconds (drivers
        that already clock their fetches)."""
        self.device_s += float(seconds)

    @property
    def host_s(self) -> float:
        """Wall-clock span duration (0.0 until the span closes)."""
        return max(self.t_end - self.t_start, 0.0)


@dataclass
class SpanRecord:
    """One closed span.  Times are ``time.perf_counter`` values; add
    the owning tracer's ``epoch`` for wall-clock seconds."""

    name: str
    span_id: int
    parent_id: int | None
    tid: int
    t_start: float
    t_end: float
    device_s: float
    attrs: dict = field(default_factory=dict)


class Tracer:
    """Thread-safe in-memory span tree for one process/run."""

    def __init__(self, registry=None, max_spans: int = MAX_SPANS):
        self._registry = registry if registry is not None else REGISTRY
        self._lock = threading.Lock()
        self._records: list[SpanRecord] = []
        self._next_id = 1
        self._tls = threading.local()
        self._tids: dict[int, int] = {}
        self._max_spans = max_spans
        self.dropped = 0
        #: close-time observers (obs/timeline.py's recorder): each is
        #: called with every closed SpanRecord; a crashing listener is
        #: dropped from the call, never raised into the traced block
        self._listeners: list = []
        self._profiler = None   # lazy: jax.profiler module, or False
        self._hbm_supported: bool | None = None
        self._hbm_high = 0
        #: wall-clock = perf_counter + epoch (lets merged multi-host
        #: traces share one absolute time base)
        self.epoch = time.time() - time.perf_counter()

    # -- recording ---------------------------------------------------------

    def _thread_state(self):
        st = getattr(self._tls, "state", None)
        if st is None:
            ident = threading.get_ident()
            with self._lock:
                tid = self._tids.setdefault(ident, len(self._tids))
            st = {"tid": tid, "stack": []}
            self._tls.state = st
        return st

    def _annotation(self, name):
        if self._profiler is None:
            try:
                import jax.profiler

                self._profiler = jax.profiler
            except Exception:  # pragma: no cover - jax unavailable
                self._profiler = False
        if self._profiler:
            return self._profiler.TraceAnnotation(name)
        return None

    @contextmanager
    def span(self, name: str, metric: str | None = None, **attrs):
        """Open a nested span; see module docstring.  ``metric`` also
        records the span into the PR-1 stage-timer registry under that
        (snake_case) stage name."""
        st = self._thread_state()
        with self._lock:
            span_id = self._next_id
            self._next_id += 1
        parent = st["stack"][-1].span_id if st["stack"] else None
        handle = SpanHandle(
            str(name), span_id, parent, st["tid"], metric, dict(attrs),
            self._registry.counter(_COMPILE_COUNTER),
        )
        handle.t_start = time.perf_counter()
        st["stack"].append(handle)
        ann = self._annotation(name)
        if ann is not None:
            ann.__enter__()
        try:
            yield handle
        except BaseException as exc:
            handle.attrs.setdefault("error", type(exc).__name__)
            raise
        finally:
            if ann is not None:
                try:
                    ann.__exit__(None, None, None)
                except Exception:  # pragma: no cover - profiler teardown
                    pass
            handle.t_end = time.perf_counter()
            if st["stack"] and st["stack"][-1] is handle:
                st["stack"].pop()
            else:  # pragma: no cover - exotic generator teardown order
                st["stack"] = [h for h in st["stack"] if h is not handle]
            self._close(handle)

    def _close(self, handle: SpanHandle) -> None:
        compiles = (self._registry.counter(_COMPILE_COUNTER)
                    - handle._compiles0)
        if compiles > 0:
            handle.attrs["compiles"] = compiles
        if self._hbm_supported is not False:
            wm = hbm_watermark()
            if wm is None:
                self._hbm_supported = False
            else:
                self._hbm_supported = True
                handle.attrs["hbm_bytes_in_use"] = wm["bytes_in_use"]
                handle.attrs["hbm_peak_bytes"] = wm["peak_bytes_in_use"]
                if wm["peak_bytes_in_use"] > self._hbm_high:
                    self._hbm_high = wm["peak_bytes_in_use"]
                    self._registry.gauge(
                        "hbm.high_water_bytes", self._hbm_high)
        rec = SpanRecord(
            name=handle.name, span_id=handle.span_id,
            parent_id=handle.parent_id, tid=handle.tid,
            t_start=handle.t_start, t_end=handle.t_end,
            device_s=handle.device_s, attrs=handle.attrs,
        )
        with self._lock:
            if len(self._records) < self._max_spans:
                self._records.append(rec)
            else:
                self.dropped += 1
                self._registry.inc("trace.spans_dropped")
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(rec)
            except Exception:
                # a broken observer must never fail the traced block
                self._registry.inc("trace.listener_errors")
        if handle.metric:
            self._registry.observe(
                handle.metric, handle.host_s, handle.device_s)

    # -- close-time listeners ----------------------------------------------

    def add_listener(self, fn) -> None:
        """Register ``fn(record: SpanRecord)`` to be called at every
        span close (obs/timeline.py hooks job-phase marks in here)."""
        with self._lock:
            self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        with self._lock:
            try:
                self._listeners.remove(fn)
            except ValueError:
                pass

    # -- access ------------------------------------------------------------

    def records(self) -> list[SpanRecord]:
        with self._lock:
            return list(self._records)

    def reset(self) -> None:
        """Start a fresh per-run span tree (ids keep increasing so
        references into an exported trace stay unambiguous)."""
        with self._lock:
            self._records.clear()
        self.dropped = 0
        self._hbm_high = 0


_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def span(name: str, metric: str | None = None, **attrs):
    """Module-level face of ``get_tracer().span(...)`` — the ONE API
    pipeline stages use (PSL006)."""
    return _TRACER.span(name, metric=metric, **attrs)


def span_cursor() -> int:
    """Position cursor into the tracer's record list; pass it to
    :func:`device_seconds` to sum device time over a window (the
    ``device_duty_cycle`` ledger, ISSUE 11)."""
    with _TRACER._lock:
        return len(_TRACER._records)


def current_span_name() -> str:
    """Name of the innermost span open on THIS thread, or "".

    Compiles run synchronously on the dispatching thread, so the
    compile ledger (obs/compilation.py) reads this as its attribution
    fallback when no explicit compile context was declared."""
    st = _TRACER._thread_state()
    return st["stack"][-1].name if st["stack"] else ""


def device_seconds(since: int = 0) -> float:
    """Total measured device (+link) seconds over the spans closed
    since a :func:`span_cursor` checkpoint.  Spans charge device time
    only where the host actually waited (``handle.block`` /
    ``add_device_time``), so ``device_seconds / wall`` is the fraction
    of the window the devices were the bottleneck — the
    ``device_duty_cycle`` gauge both drivers and the worker drain
    emit.  A tracer reset (or the MAX_SPANS cap) can shrink the
    record list below ``since``; the slice is then empty, never an
    error."""
    return sum(r.device_s for r in _TRACER.records()[since:])


# --------------------------------------------------------------------------
# Chrome trace-event export
# --------------------------------------------------------------------------

def chrome_events(records, process_index: int = 0,
                  epoch: float = 0.0) -> list[dict]:
    """Balanced ``B``/``E`` trace events (µs timestamps, monotonic per
    tid) plus ``M`` metadata, loadable in Perfetto/chrome://tracing.

    Spans nest properly per thread by construction; the emitter walks
    each thread's span forest depth-first so every ``B`` has its ``E``
    and timestamps never run backwards (children are clamped into
    their parent's interval against float rounding).
    """
    by_id = {r.span_id: r for r in records}
    children: dict[int, list[SpanRecord]] = {}
    roots: dict[int, list[SpanRecord]] = {}
    for r in sorted(records, key=lambda r: (r.t_start, r.span_id)):
        if r.parent_id is not None and r.parent_id in by_id:
            children.setdefault(r.parent_id, []).append(r)
        else:
            roots.setdefault(r.tid, []).append(r)
    events: list[dict] = [{
        "ph": "M", "name": "process_name", "pid": process_index,
        "tid": 0, "args": {"name": f"host {process_index}"},
    }]

    def us(t: float) -> float:
        return round((t + epoch) * 1e6, 3)

    def emit(r: SpanRecord, lo: float, hi: float, cursor: float) -> float:
        ts_b = min(max(us(r.t_start), lo, cursor), hi)
        args = {"span_id": r.span_id,
                "device_ms": round(r.device_s * 1e3, 3)}
        if r.parent_id is not None:
            args["parent_id"] = r.parent_id
        args.update(r.attrs)
        events.append({
            "name": r.name, "cat": "peasoup", "ph": "B", "ts": ts_b,
            "pid": process_index, "tid": r.tid, "args": args,
        })
        cursor = ts_b
        for c in children.get(r.span_id, []):
            cursor = emit(c, ts_b, max(us(r.t_end), ts_b), cursor)
        ts_e = min(max(us(r.t_end), cursor), max(hi, cursor))
        events.append({
            "name": r.name, "ph": "E", "ts": ts_e,
            "pid": process_index, "tid": r.tid,
        })
        return ts_e

    for tid in sorted(roots):
        cursor = float("-inf")
        for r in roots[tid]:
            cursor = emit(r, float("-inf"), float("inf"), cursor)
    return events


def local_trace_payload(tracer: Tracer | None = None) -> bytes:
    """This process's spans as one opaque JSON payload (pid-tagged with
    ``jax.process_index()``) — the unit the multihost gather ships."""
    tracer = tracer if tracer is not None else _TRACER
    pi = _process_index()
    return json.dumps({
        "v": 1,
        "process_index": pi,
        "dropped": tracer.dropped,
        "events": chrome_events(tracer.records(), process_index=pi,
                                epoch=tracer.epoch),
    }).encode()


def write_merged_trace(path: str, tracer: Tracer | None = None,
                       gather=None,
                       process_index: int | None = None) -> str | None:
    """Gather every host's spans and write ONE merged Chrome trace.

    ``gather`` maps this process's payload (bytes) to the ordered list
    of all processes' payloads; it defaults to
    ``parallel.multihost.gather_host_payloads`` (the real allgather —
    single-process runs never touch collectives).  Only process 0
    writes; other processes participate in the gather and return None.
    Telemetry I/O failures warn, never raise.
    """
    payload = local_trace_payload(tracer)
    if gather is None:
        from ..parallel.multihost import gather_host_payloads as gather
    parts = gather(payload)
    pi = process_index if process_index is not None else _process_index()
    if pi != 0:
        return None
    events: list[dict] = []
    n_parts = 0
    for part in parts:
        try:
            doc = json.loads(part)
        except (TypeError, ValueError):
            continue
        n_parts += 1
        events.extend(doc.get("events", []))
    # one shared zero point: the earliest span across every host
    ts0 = min((e["ts"] for e in events
               if "ts" in e and e.get("ph") != "M"), default=0.0)
    for e in events:
        if "ts" in e:
            e["ts"] = round(e["ts"] - ts0, 3)
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {"tool": "peasoup-tpu", "n_processes": n_parts},
    }
    try:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        atomic_write_json(path, doc, trailing_newline=True)
    except OSError as exc:
        import warnings

        warnings.warn(f"could not write trace {path!r}: {exc}")
        return None
    return path


# --------------------------------------------------------------------------
# span table (run_report.json)
# --------------------------------------------------------------------------

def span_table(records=None) -> dict:
    """Per-name aggregate {count, total_s, self_s, device_s}, ordered
    by descending self time (total minus direct children) — the
    "where did the run actually go" table run_report.json carries."""
    records = list(records if records is not None else _TRACER.records())
    by_id = {r.span_id: r for r in records}
    child_time: dict[int, float] = {}
    for r in records:
        if r.parent_id in by_id:
            child_time[r.parent_id] = (
                child_time.get(r.parent_id, 0.0) + (r.t_end - r.t_start))
    agg: dict[str, dict] = {}
    for r in records:
        rec = agg.setdefault(
            r.name,
            {"count": 0, "total_s": 0.0, "self_s": 0.0, "device_s": 0.0})
        dur = r.t_end - r.t_start
        rec["count"] += 1
        rec["total_s"] += dur
        rec["self_s"] += max(dur - child_time.get(r.span_id, 0.0), 0.0)
        rec["device_s"] += r.device_s
    return {
        name: {k: (v if k == "count" else round(v, 6))
               for k, v in rec.items()}
        for name, rec in sorted(agg.items(),
                                key=lambda kv: -kv[1]["self_s"])
    }
