# Convenience entry points; CI runs `make lint test`.
# JAX_PLATFORMS=cpu keeps both off any attached accelerator.

PY ?= python

.PHONY: lint test bench

lint:
	JAX_PLATFORMS=cpu $(PY) -m peasoup_tpu.analysis

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

bench:
	$(PY) bench.py
