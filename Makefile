# Convenience entry points; CI runs `make lint test`.
# JAX_PLATFORMS=cpu keeps both off any attached accelerator.

PY ?= python
TUTORIAL ?= /root/reference/example_data/tutorial.fil
SMOKE_DIR ?= /tmp/peasoup-trace-smoke
SERVE_SMOKE_DIR ?= /tmp/peasoup-serve-smoke
FLEET_SMOKE_DIR ?= /tmp/peasoup-fleet-smoke
BATCH_SMOKE_DIR ?= /tmp/peasoup-batch-smoke
HEALTH_SMOKE_DIR ?= /tmp/peasoup-health-smoke
PIPELINE_SMOKE_DIR ?= /tmp/peasoup-pipeline-smoke
LOADGEN_SMOKE_DIR ?= /tmp/peasoup-loadgen-smoke
JERK_SMOKE_DIR ?= /tmp/peasoup-jerk-smoke
SENSITIVITY_SMOKE_DIR ?= /tmp/peasoup-sensitivity-smoke
CHAOS_SMOKE_DIR ?= /tmp/peasoup-chaos-smoke
OBS_SMOKE_DIR ?= /tmp/peasoup-obs-smoke
ANALYSIS_SMOKE_DIR ?= /tmp/peasoup-analysis-smoke
COLDSTART_SMOKE_DIR ?= /tmp/peasoup-coldstart-smoke
LINEAGE_SMOKE_DIR ?= /tmp/peasoup-lineage-smoke

.PHONY: lint test bench perf-gate peaks-sweep-smoke trace-smoke serve-smoke fleet-smoke batch-smoke health-smoke pipeline-smoke loadgen-smoke jerk-smoke sensitivity-smoke chaos-smoke obs-smoke analysis-smoke coldstart-smoke lineage-smoke

# covers the whole tree incl. ops/peaks_pallas.py against the
# committed (near-empty) baseline — new kernels land lint-clean, no
# grandfathering (tests/test_lint.py pins this per-file too)
lint:
	JAX_PLATFORMS=cpu $(PY) -m peasoup_tpu.analysis

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

bench:
	$(PY) bench.py

# noise-aware perf regression gate over benchmarks/history.jsonl (+ the
# legacy BENCH_r0*.json artifacts): fails when the newest record's gate
# metric exceeds the trailing-window median by the threshold factor.
# Besides wall-clock (e2e_s) the gate also checks the per-stage device
# -time columns (peaks_device_s, search_device_s — ISSUE 6): a sort
# -wall regression must trip even when tunnel jitter hides it from
# wall-clock.  `python bench.py --gate` is the run-then-gate spelling
# for hardware CI.
perf-gate:
	JAX_PLATFORMS=cpu $(PY) -m peasoup_tpu.tools.perf_report --gate

# peak-extraction shape-stability sweep, one subprocess per
# (C, stop, cap) cell so a backend crash is recorded as an unsafe cell
# instead of killing the sweep (full grid writes
# benchmarks/peaks_sweep.json; the smoke runs one safe cell)
peaks-sweep-smoke:
	JAX_PLATFORMS=cpu $(PY) benchmarks/peaks_sweep.py --quick \
	    --out /tmp/peasoup-peaks-sweep.json --iters 4

# span-tracing smoke test: a tutorial run must write a parseable
# Chrome trace whose span names cover the five pipeline stages
trace-smoke:
	rm -rf $(SMOKE_DIR)
	JAX_PLATFORMS=cpu $(PY) -m peasoup_tpu.cli -i $(TUTORIAL) \
	    -o $(SMOKE_DIR) --dm_start 0 --dm_end 60 --acc_start -5 \
	    --acc_end 5 --acc_pulse_width 64000 --npdmp 2 --limit 50 \
	    --single_device --trace_json $(SMOKE_DIR)/trace.json
	JAX_PLATFORMS=cpu $(PY) -m peasoup_tpu.tools.trace_report \
	    $(SMOKE_DIR)/trace.json \
	    --require Dedisperse DM-Loop Accel-Search Distill Folding

# survey-scheduler smoke test: spool 3 synthetic observations (one
# truncated), drain a worker, assert 2 done + 1 quarantined + store
# candidates + a serve throughput record in benchmarks/history.jsonl,
# then crash a job mid-search and assert the retry resumes from its
# per-job checkpoint instead of recomputing
serve-smoke:
	JAX_PLATFORMS=cpu $(PY) -m peasoup_tpu.tools.serve_smoke \
	    --dir $(SERVE_SMOKE_DIR)

# fleet control-plane smoke test: two real fleet-worker processes (fake
# membership) drain one spool — 2 done + 1 quarantined with zero
# double-claims and per-host store shards — then a worker is SIGKILLed
# mid-job and `requeue --expired` recovers its lease-expired job with
# the attempt history intact; merged-shard coincidence must equal a
# single store and `status --fleet` must aggregate every host
fleet-smoke:
	JAX_PLATFORMS=cpu $(PY) -m peasoup_tpu.tools.fleet_smoke \
	    --dir $(FLEET_SMOKE_DIR)

# batched-dispatch smoke test: drain 4 same-geometry + 1 odd-geometry
# observations with `worker --batch 4` and assert ONE batched dispatch
# (+1 singleton for the odd bucket), all 5 done, fewer fused dispatches
# than a sequential drain, per-beam store records bit-identical to the
# batch=1 reference, and a ledger record with batch_fill >= 2
batch-smoke:
	JAX_PLATFORMS=cpu $(PY) -m peasoup_tpu.tools.batch_smoke \
	    --dir $(BATCH_SMOKE_DIR)

# telemetry/health-plane smoke test: two real fleet-worker processes
# drain with fast telemetry — both hosts must leave ts- shards whose
# samples carry queue depths, `health` must exit 0, and the sampler's
# self-measured overhead must stay <1% of the drain wall-clock; then a
# worker is SIGKILLed mid-job and `health` must exit nonzero with a
# crit stale_host finding until `requeue --expired` + a re-drain bring
# the fleet back to ok
health-smoke:
	JAX_PLATFORMS=cpu $(PY) -m peasoup_tpu.tools.health_smoke \
	    --dir $(HEALTH_SMOKE_DIR)

# dispatch-pipeline smoke test: drain 4 chunked-driver observations at
# pipeline_depth=1 then depth=2 and assert both drains measure a sane
# device_duty_cycle gauge, record chunk.pipeline_depth, write a serve
# ledger record carrying the duty gauge, and produce BIT-IDENTICAL
# per-source candidates (the pipeline is pure scheduling)
pipeline-smoke:
	JAX_PLATFORMS=cpu $(PY) -m peasoup_tpu.tools.pipeline_smoke \
	    --dir $(PIPELINE_SMOKE_DIR)

# load-observatory smoke test: an open-loop two-rate saturation sweep
# (15 jobs/point incl. one poison job) against two real fleet-worker
# processes — saturation_report.json must carry >=2 rate points with
# phase-decomposed p50/p95/p99 sojourn, the poison job must be
# quarantined WITHOUT entering the percentile pool, a kind:"loadgen"
# ledger record must carry the detected knee, the `timeline <job_id>`
# verb must render a waterfall whose phase sum equals the sojourn, and
# the timeline plane's own cost must stay <1% of drain wall-clock
loadgen-smoke:
	JAX_PLATFORMS=cpu $(PY) -m peasoup_tpu.tools.loadgen --smoke \
	    --dir $(LOADGEN_SMOKE_DIR)

# jerk-search smoke test (ISSUE 13): zero-jerk runs must be
# bit-identical to the accel-only default; a {-j, 0, +j} jerk grid
# must recover a synthetic jerk-smeared pulse the accel-only grid
# misses; forced u8/bf16 trial lattices must keep the recovery and
# write a parity-gated lattice sidecar that `auto` resolution honors
# (and refuses when a verdict fails); a kind:"jerk_smoke" ledger
# record must round-trip
jerk-smoke:
	JAX_PLATFORMS=cpu $(PY) -m peasoup_tpu.tools.jerk_smoke \
	    --dir $(JERK_SMOKE_DIR)

# sensitivity-observatory smoke test (ISSUE 14): a 3-point injected-SNR
# sweep must recover the bright injections, miss the faintest, attach
# a monotone per-stage SNR budget to every cell and append exactly one
# kind:"sensitivity" ledger record; a recovered canary drain must pass
# `health` while a missed canary must trip canary_recovery to crit
# (nonzero exit) until a clean re-drain clears it; canary candidates
# must stay out of science store reads
sensitivity-smoke:
	JAX_PLATFORMS=cpu $(PY) -m peasoup_tpu.tools.sensitivity --smoke \
	    --dir $(SENSITIVITY_SMOKE_DIR)

# chaos smoke test (ISSUE 15): seeded fault plan (worker SIGKILL
# mid-job, one poison input, one over-quota tenant) against a live
# supervised fleet under two-rate traffic — the supervisor must
# detect/reap/respawn, health must return to exit 0 inside the
# budget with zero jobs lost or double-run, the flooding tenant must
# be deferred with a typed AdmissionError while the fair-share tenant
# completes its whole quota, and a control phase with NO supervisor
# must leave the same fault at health exit 1
chaos-smoke:
	JAX_PLATFORMS=cpu $(PY) -m peasoup_tpu.tools.chaos --smoke \
	    --dir $(CHAOS_SMOKE_DIR)

# flight-recorder smoke test (ISSUE 16): the obs verb family against
# the checked-in fixtures — `obs diff` must regenerate the trace
# summary from the two fixture run reports, `obs baseline` must pass
# (exit 0) over the clean fixture ledger, and ingest/query/top must
# round-trip every fixture stream through a scratch warehouse
obs-smoke:
	rm -rf $(OBS_SMOKE_DIR)
	mkdir -p $(OBS_SMOKE_DIR)
	JAX_PLATFORMS=cpu $(PY) -m peasoup_tpu.cli obs diff \
	    benchmarks/fixtures/run_report_r5.json \
	    benchmarks/fixtures/run_report_r6.json \
	    --out $(OBS_SMOKE_DIR)/trace_summary.md
	cmp $(OBS_SMOKE_DIR)/trace_summary.md benchmarks/trace_summary_r6.md
	JAX_PLATFORMS=cpu $(PY) -m peasoup_tpu.cli obs baseline \
	    --ledger benchmarks/fixtures/history_fixture.jsonl
	JAX_PLATFORMS=cpu $(PY) -m peasoup_tpu.cli obs ingest \
	    --dir $(OBS_SMOKE_DIR)/warehouse \
	    --report benchmarks/fixtures/run_report_r5.json \
	    --report benchmarks/fixtures/run_report_r6.json \
	    --ledger benchmarks/fixtures/history_fixture.jsonl
	JAX_PLATFORMS=cpu $(PY) -m peasoup_tpu.cli obs top \
	    --dir $(OBS_SMOKE_DIR)/warehouse -n 5 --metric span.device_s
	JAX_PLATFORMS=cpu $(PY) -m peasoup_tpu.cli obs query \
	    --dir $(OBS_SMOKE_DIR)/warehouse --stage peaks --limit 10

# cold-start observatory smoke test (ISSUE 18): a cold worker drain
# must measure cold_to_first_candidate_s and decompose it into
# read/trace/compile/execute phases that partition the total, the
# spool compile ledger must attribute every backend compile to a
# program + geometry fingerprint, and a warm drain of the same
# geometry in the same process must ledger ZERO new compiles
coldstart-smoke:
	JAX_PLATFORMS=cpu $(PY) -m peasoup_tpu.tools.coldstart_smoke \
	    --dir $(COLDSTART_SMOKE_DIR)

# concurrency & contracts prover smoke test (ISSUE 17): writes a
# deliberately broken fixture tree and asserts each of PSL010-PSL013
# fires on it (nonzero exit naming the rule), `--rules` subsetting
# works, and the real tree stays clean under the same four rules — a
# detector that cannot detect is worse than none
analysis-smoke:
	JAX_PLATFORMS=cpu $(PY) -m peasoup_tpu.tools.analysis_smoke \
	    --dir $(ANALYSIS_SMOKE_DIR)

# candidate-provenance smoke test (ISSUE 19): a real drain must leave
# a lineage ledger whose funnel conserves EXACTLY
# (decoded == absorbed + cut + emitted), the `why` verb must
# reconstruct a stored candidate's full decision chain from only its
# store record, distilled candidates must be bit-identical with
# lineage on vs --no-lineage, the writer's self-measured overhead
# must stay <1% of drain wall-clock, and a deliberately widened
# harmonic tolerance must trip the distill_collapse health rule
lineage-smoke:
	JAX_PLATFORMS=cpu $(PY) -m peasoup_tpu.tools.lineage_smoke \
	    --dir $(LINEAGE_SMOKE_DIR)
